"""jit compile/retrace watchdog + per-compile cost capture.

A retrace storm — a jitted function recompiling every call because a
static argument or a weak-typed shape keeps changing — is invisible at
the Python level: the run just gets mysteriously slower. `WatchedJit`
wraps a compiled function and watches its executable cache
(`_cache_size()`, present on jax's PjitFunction; absent-API fallback:
only the first call counts as a compile): a call that GROWS the cache
was a cache miss, its wall time (compile + first execution — jax does
not expose the split) is emitted as a `jit_compile:<name>` span on the
installed timeline, and once the per-function miss count passes
`storm_threshold` every further miss emits a `retrace_storm` mark so
the report/timeline flag it.

Since ISSUE 7 every detected miss ALSO emits one `compile` record into
the same stream: the measured `wall_s` plus the compiled program's
bill from `obs/compile.capture_compile` — cache-warm lower/compile
split, `cost_analysis()` FLOPs/bytes, `memory_analysis()`
argument/output/temp/peak bytes — all null-degrading where the jax
version lacks the API. The capture replays lower+compile from
ABSTRACT shapes (metadata survives donation; no buffer is re-read), so
it is observation-only — and because the replay is a second full XLA
compile, it runs on the FIRST miss per jit only: later misses (scan-
length variants, retrace storms) record their measured wall_s without
it, bounding the cost of watching to one extra compile per watched jit
per process and never doubling the per-call cost of the very pathology
the storm flag exists to catch.

The wrapper is a transparent passthrough — same positional/keyword
calling convention, same outputs, donation semantics untouched (they
live on the wrapped jit) — and does NOTHING unless a timeline is
installed, so the default path stays exactly the pre-observatory one.
graftlint's engine resolves `self._f = watch_jit(jax.jit(...), ...)`
assignments through the wrapper, so JGL004 donation tracking keeps
working on watched jits.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Callable, Optional

from factorvae_tpu.utils.logging import current_timeline

# Misses beyond this per function flag a retrace storm. The legitimate
# compile count for an epoch function is tiny (one per distinct scan
# length: whole epochs plus possibly one shorter tail chunk).
STORM_THRESHOLD = 3

# Cost/memory capture master switch. The "one replay per jit" bound
# assumes long-lived jits; a process that builds DOZENS of short-lived
# trainers (the autotune race: fresh WatchedJits per candidate) would
# pay the replay — a second full XLA compile — once per candidate and
# nearly double its wall clock. Such paths wrap themselves in
# `capture_disabled()`: records keep their measured wall_s (what the
# race provenance consumes), only the replayed bill is skipped.
_CAPTURE = True


@contextlib.contextmanager
def capture_disabled():
    """Suspend the per-compile cost/memory replay (wall_s-only
    records) for the duration of the block."""
    global _CAPTURE
    prev = _CAPTURE
    _CAPTURE = False
    try:
        yield
    finally:
        _CAPTURE = prev


# ---------------------------------------------------------------------------
# Persistent-compilation-cache classification (ISSUE 8). A pjit cache
# miss in a FRESH process is not necessarily an XLA compile: with a
# persistent compilation cache (plan.setup_compilation_cache) the
# executable may be deserialized from disk. jax announces that through
# its monitoring events; a listener counts them so WatchedJit can emit
# `compile_cached` instead of `compile` for disk-served builds — the
# warm-restart contract a scoring daemon is judged on (zero `compile`
# records on the second process, tests/test_serve.py). OPT-IN
# (`track_persistent_cache()`): default-path consumers (tests that
# count `compile` records, training runs sharing the test rig's cache)
# keep the pre-ISSUE-8 event taxonomy unless a serving/bench path asks.
# ---------------------------------------------------------------------------

_PCACHE = {"hits": 0, "misses": 0}
_PCACHE_CLASSIFY = False

# Process-wide tally of emitted compile records by taxonomy, for the
# /metrics exposition (obs/metrics.py): a warm-restarted daemon with a
# persistent cache scrapes compile==0 / compile_cached>0 — the same
# contract tests/test_serve.py pins on the RUN stream, now visible to
# a scraper without parsing JSONL. Counts only what was LOGGED (i.e.
# with a timeline installed): the no-timeline path stays untouched.
_EVENT_COUNTS = {"compile": 0, "compile_cached": 0}

# Guards BOTH module tallies (graftlint JGL009): jits dispatch — and
# therefore bump these counters — on whatever thread scores (the HTTP
# handler, the stdin tick loop) while `GET /metrics` snapshots them
# from its own scrape; `dict[k] += 1` is a read-modify-write that
# loses updates under that interleaving. One uncontended lock per
# COMPILE (not per call) costs nothing against a multi-second trace.
_COUNTS_LOCK = threading.Lock()


def compile_event_counts() -> dict:
    """Copy of this process's compile-record tally by taxonomy."""
    with _COUNTS_LOCK:
        return dict(_EVENT_COUNTS)


def _pcache_listener(event: str, **kwargs) -> None:
    with _COUNTS_LOCK:
        if event == "/jax/compilation_cache/cache_hits":
            _PCACHE["hits"] += 1
        elif event == "/jax/compilation_cache/cache_misses":
            _PCACHE["misses"] += 1


def track_persistent_cache() -> bool:
    """Enable persistent-cache classification of compile records for
    this process. Returns True when the jax monitoring hook is
    available (idempotent); False leaves the taxonomy unchanged."""
    global _PCACHE_CLASSIFY
    if _PCACHE_CLASSIFY:
        return True
    try:
        from jax import monitoring

        monitoring.register_event_listener(_pcache_listener)
    except Exception:
        return False
    _PCACHE_CLASSIFY = True
    return True


class WatchedJit:
    def __init__(self, fn: Callable, name: str,
                 storm_threshold: int = STORM_THRESHOLD):
        self._fn = fn
        self.name = name
        self.storm_threshold = storm_threshold
        self.calls = 0
        self.compiles = 0
        self.total_compile_s = 0.0
        # Most recent `compile` record's fields (tests / provenance).
        self.last_compile: Optional[dict] = None
        # Guards the per-instance counters (JGL009): a watched jit can
        # be dispatched from the serving thread while /metrics-style
        # readers snapshot calls/compiles from another.
        self._lock = threading.Lock()

    def __getattr__(self, attr: str) -> Any:
        # Transparent delegation: jit-surface APIs (.lower(),
        # .clear_cache(), ...) keep working on a watched function
        # (tests/test_parallel.py lowers the trainer's epoch jit to
        # assert sharded HLO).
        return getattr(self._fn, attr)

    def _cache_size(self) -> Optional[int]:
        f = getattr(self._fn, "_cache_size", None)
        if not callable(f):
            return None
        try:
            return int(f())
        except Exception:
            return None

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        tl = current_timeline()
        if tl is None:
            return self._fn(*args, **kwargs)
        from factorvae_tpu.obs import compile as compilelib

        before = self._cache_size()
        with _COUNTS_LOCK:
            pc0 = dict(_PCACHE)
        t0 = time.perf_counter()
        out = self._fn(*args, **kwargs)
        t1 = time.perf_counter()
        with self._lock:
            self.calls += 1
            calls = self.calls
        missed = (calls == 1 if before is None
                  else (self._cache_size() or 0) > before)
        if missed:
            wall = round(t1 - t0, 6)
            with self._lock:
                self.compiles += 1
                compiles = self.compiles
                self.total_compile_s = round(
                    self.total_compile_s + wall, 6)
                total_compile_s = self.total_compile_s
            tl.span_at(
                f"jit_compile:{self.name}", t0, t1, cat="compile",
                resource="compile", compiles=compiles)
            # The per-compile program bill (null-degrading; ISSUE 7).
            # `wall_s` is the authoritative in-call measurement and is
            # ALWAYS nonnull; the capture fields ride along when the
            # jax version exposes them. The replay is a SECOND full XLA
            # compile (there is no in-process executable cache across
            # lower() calls), so only the FIRST miss per jit pays it —
            # later misses (legitimate scan-length variants, retrace
            # storms) record wall_s only, bounding the cost of watching
            # to one extra compile per watched jit per process. The
            # abstract snapshot happens AFTER the call: shape/dtype
            # metadata survives donation (only the buffer is deleted).
            # With classification on (serving/bench paths), a miss
            # whose executable came off the persistent disk cache —
            # the in-call window saw cache_hits grow and no fresh
            # cache_misses — records as `compile_cached`: the process
            # built nothing, it deserialized. Everything else stays a
            # `compile` record exactly as before. Judged BEFORE the
            # capture replay below, whose second XLA compile would
            # pollute the counter window.
            event = "compile"
            with _COUNTS_LOCK:
                pc1 = dict(_PCACHE)
            if (_PCACHE_CLASSIFY
                    and pc1["hits"] > pc0["hits"]
                    and pc1["misses"] == pc0["misses"]):
                event = "compile_cached"
            cap = {}
            if compiles == 1 and _CAPTURE:
                try:
                    cap = compilelib.capture_compile(
                        self._fn, compilelib.abstractify(args),
                        compilelib.abstractify(kwargs),
                        want_text=True)
                except Exception:  # graftlint: disable=JGL007 capture is best-effort telemetry; failure degrades to an empty compile record that IS logged unconditionally below
                    cap = {}
            # Strip the non-JSON artifacts (HLO text is megabytes; the
            # sharding pytrees aren't serializable) OUT of the metric
            # record and INTO the compiled-view store, keyed by watch
            # name — the semantic lint backend (analysis/ir.py) audits
            # this jit off the stash instead of paying a third compile.
            view = {k: cap.pop(k) for k in
                    ("hlo_text", "input_shardings", "output_shardings")
                    if k in cap}
            if view.get("hlo_text"):
                compilelib.record_compiled_view(self.name, view)
            last = dict(cap, fn=self.name, wall_s=wall,
                        compiles=compiles)
            with self._lock:
                self.last_compile = last
            with _COUNTS_LOCK:
                _EVENT_COUNTS[event] += 1
            tl.logger.log(event, _echo=False, **last)
            if compiles > self.storm_threshold:
                tl.event(
                    "retrace_storm", cat="compile", resource="compile",
                    fn=self.name, compiles=compiles, calls=calls,
                    total_compile_s=total_compile_s,
                    note="cache misses keep accruing — a static arg or "
                         "shape is changing per call")
        return out


def watch_jit(fn: Callable, name: str,
              storm_threshold: int = STORM_THRESHOLD) -> WatchedJit:
    """Wrap a jitted callable with the compile watchdog."""
    return WatchedJit(fn, name, storm_threshold=storm_threshold)
