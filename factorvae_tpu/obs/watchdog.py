"""jit compile/retrace watchdog.

A retrace storm — a jitted function recompiling every call because a
static argument or a weak-typed shape keeps changing — is invisible at
the Python level: the run just gets mysteriously slower. `WatchedJit`
wraps a compiled function and watches its executable cache
(`_cache_size()`, present on jax's PjitFunction; absent-API fallback:
only the first call counts as a compile): a call that GROWS the cache
was a cache miss, its wall time (compile + first execution — jax does
not expose the split) is emitted as a `jit_compile:<name>` span on the
installed timeline, and once the per-function miss count passes
`storm_threshold` every further miss emits a `retrace_storm` mark so
the report/timeline flag it.

The wrapper is a transparent passthrough — same positional/keyword
calling convention, same outputs, donation semantics untouched (they
live on the wrapped jit) — and does NOTHING unless a timeline is
installed, so the default path stays exactly the pre-observatory one.
graftlint's engine resolves `self._f = watch_jit(jax.jit(...), ...)`
assignments through the wrapper, so JGL004 donation tracking keeps
working on watched jits.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Optional

from factorvae_tpu.utils.logging import current_timeline

# Misses beyond this per function flag a retrace storm. The legitimate
# compile count for an epoch function is tiny (one per distinct scan
# length: whole epochs plus possibly one shorter tail chunk).
STORM_THRESHOLD = 3


class WatchedJit:
    def __init__(self, fn: Callable, name: str,
                 storm_threshold: int = STORM_THRESHOLD):
        self._fn = fn
        self.name = name
        self.storm_threshold = storm_threshold
        self.calls = 0
        self.compiles = 0

    def __getattr__(self, attr: str) -> Any:
        # Transparent delegation: jit-surface APIs (.lower(),
        # .clear_cache(), ...) keep working on a watched function
        # (tests/test_parallel.py lowers the trainer's epoch jit to
        # assert sharded HLO).
        return getattr(self._fn, attr)

    def _cache_size(self) -> Optional[int]:
        f = getattr(self._fn, "_cache_size", None)
        if not callable(f):
            return None
        try:
            return int(f())
        except Exception:
            return None

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        tl = current_timeline()
        if tl is None:
            return self._fn(*args, **kwargs)
        before = self._cache_size()
        t0 = time.perf_counter()
        out = self._fn(*args, **kwargs)
        t1 = time.perf_counter()
        self.calls += 1
        missed = (self.calls == 1 if before is None
                  else (self._cache_size() or 0) > before)
        if missed:
            self.compiles += 1
            tl.span_at(
                f"jit_compile:{self.name}", t0, t1, cat="compile",
                resource="compile", compiles=self.compiles)
            if self.compiles > self.storm_threshold:
                tl.event(
                    "retrace_storm", cat="compile", resource="compile",
                    fn=self.name, compiles=self.compiles, calls=self.calls,
                    note="cache misses keep accruing — a static arg or "
                         "shape is changing per call")
        return out


def watch_jit(fn: Callable, name: str,
              storm_threshold: int = STORM_THRESHOLD) -> WatchedJit:
    """Wrap a jitted callable with the compile watchdog."""
    return WatchedJit(fn, name, storm_threshold=storm_threshold)
