"""Compiled-program capture: what did XLA actually build, and at what
cost?

The run observatory (PR 5) watches *runtime* — probes, spans, flags —
but nothing about the programs behind them: how long each jit took to
build, how many FLOPs/bytes it schedules, how much HBM it reserves.
This module owns that capture (ISSUE 7):

- **Guarded accessors** over the jax AOT surface. `cost_analysis()` /
  `memory_analysis()` / `as_text()` availability and return shape vary
  across jax versions and backends (list-of-dict vs dict,
  `CompiledMemoryStats` vs dict, missing entirely, or returning None).
  Every accessor here degrades to None — it NEVER raises — so a version
  skew turns a telemetry field null instead of killing a run. The
  accessors are the one shared implementation (tests/test_bench.py's
  FLOPs-model oracle reads through them too).

- **`capture_compile`**: given a jitted callable and the ABSTRACT
  shapes of one call (`abstractify` snapshots them as
  `jax.ShapeDtypeStruct`s, never touching buffers — donation only
  deletes the buffer, the metadata survives), replay `lower()` +
  `compile()` separately timed and extract the cost/memory analyses.
  The replay is a SECOND full XLA compile — there is no in-process
  executable cache across `lower()` calls (only the optional on-disk
  persistent cache) — which is why the watchdog invokes this once per
  jit, on the first detected miss. The authoritative wall time of the
  real build is the watchdog's measured `wall_s` (compile + first
  execution — jax does not expose that split in the call path); the
  replay's `lower_s`/`compile_s` give the trace-vs-XLA split of an
  equivalent build.

- **Compiled-view store** (`record_compiled_view`/`compiled_view`): the
  watchdog's first-miss capture stashes the non-JSON artifacts — the
  post-SPMD HLO text plus the executable's input/output sharding
  pytrees — under the jit's watch name, so the semantic lint backend
  (`analysis/ir.py`, ISSUE 18) audits a program that already compiled
  without paying a SECOND lower+compile. First-miss-only discipline is
  preserved: the store only ever holds what a capture already built.

`obs/watchdog.WatchedJit` emits one `compile` record per detected
cache miss into the same RUN.jsonl stream as the metrics, carrying
these fields; `obs.report` / `obs.timeline` render and budget-check
them. Everything here is observation-only: abstract shapes in, JSON
fields out, params and numerics untouched (the bitwise discipline of
tests/test_obs.py).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional

__all__ = [
    "abstractify",
    "capture_compile",
    "clear_compiled_views",
    "compiled_view",
    "guarded_compiled_text",
    "guarded_cost_analysis",
    "guarded_memory_analysis",
    "record_compiled_view",
]

# watch name -> {"hlo_text", "input_shardings", "output_shardings"}.
# Written once per jit (first detected miss); readers get the dict
# as-is. The lock only guards the map, not the (immutable) views.
_VIEW_LOCK = threading.Lock()
_COMPILED_VIEWS: Dict[str, dict] = {}


def record_compiled_view(name: str, view: dict) -> None:
    """Stash one jit's compiled artifacts under its watch name. Last
    write wins — a re-miss (shape change) replaces the stale view."""
    if not name:
        return
    with _VIEW_LOCK:
        _COMPILED_VIEWS[name] = dict(view)


def compiled_view(name: str) -> Optional[dict]:
    """The stashed compiled view for `name`, or None if no watchdog
    capture has run for that jit in this process."""
    with _VIEW_LOCK:
        return _COMPILED_VIEWS.get(name)


def clear_compiled_views() -> None:
    """Drop every stashed view (tests isolating compile-count checks)."""
    with _VIEW_LOCK:
        _COMPILED_VIEWS.clear()


def _as_float(v) -> Optional[float]:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    return f


def guarded_cost_analysis(compiled: Any) -> Optional[dict]:
    """`compiled.cost_analysis()` normalized to ONE flat {str: float}
    dict, or None where the jax version / backend doesn't support it.

    Handles every observed return shape: a dict, a list of per-module
    dicts (older jaxlibs — the first entry is the program), None, a
    missing attribute, or an accessor that raises. Never raises.
    """
    fn = getattr(compiled, "cost_analysis", None)
    if not callable(fn):
        return None
    try:
        ca = fn()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    out = {}
    for k, v in ca.items():
        f = _as_float(v)
        if f is not None:
            out[str(k)] = f
    return out or None


# CompiledMemoryStats attribute -> record key. `peak_bytes` is derived:
# argument + output + temp - alias (donated buffers alias in place) — an
# estimate of the executable's device-memory high water, not a measured
# allocator peak (that is obs/memory.watermark territory).
_MEMORY_FIELDS = {
    "argument_size_in_bytes": "argument_bytes",
    "output_size_in_bytes": "output_bytes",
    "temp_size_in_bytes": "temp_bytes",
    "alias_size_in_bytes": "alias_bytes",
    "generated_code_size_in_bytes": "generated_code_bytes",
}


def guarded_memory_analysis(compiled: Any) -> Optional[dict]:
    """`compiled.memory_analysis()` normalized to
    {argument_bytes, output_bytes, temp_bytes, alias_bytes,
    generated_code_bytes, peak_bytes}, or None where unsupported.
    Accepts the `CompiledMemoryStats` object (attributes) or a dict
    (some backends); never raises."""
    fn = getattr(compiled, "memory_analysis", None)
    if not callable(fn):
        return None
    try:
        ma = fn()
    except Exception:
        return None
    if ma is None:
        return None
    out: dict = {}
    for attr, key in _MEMORY_FIELDS.items():
        v = (ma.get(attr) if isinstance(ma, dict)
             else getattr(ma, attr, None))
        out[key] = _as_float(v)
    if all(v is None for v in out.values()):
        return None
    known = [out[k] for k in ("argument_bytes", "output_bytes",
                              "temp_bytes") if out.get(k) is not None]
    if known:
        peak = sum(known)
        if out.get("alias_bytes"):
            peak -= out["alias_bytes"]
        out["peak_bytes"] = max(peak, 0.0)
    else:
        out["peak_bytes"] = None
    return out


def guarded_compiled_text(compiled: Any) -> Optional[str]:
    """Post-optimization (post-SPMD-partitioning) HLO text of a compiled
    executable, or None where unsupported — the input of the
    obs/comms.py collective scan. Never raises."""
    fn = getattr(compiled, "as_text", None)
    if not callable(fn):
        return None
    try:
        text = fn()
    except Exception:
        return None
    return text if isinstance(text, str) else None


def abstractify(tree):
    """Pytree of `jax.ShapeDtypeStruct`s mirroring `tree`'s arrays —
    shape/dtype metadata only, safe to take BEFORE a donating call and
    to lower() from AFTER it (a donated buffer must never be re-read;
    lowering from abstract values reads nothing)."""
    import jax
    import numpy as np

    def one(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return jax.ShapeDtypeStruct(x.shape, x.dtype)
        return jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype)

    return jax.tree_util.tree_map(one, tree)


def capture_compile(fn: Callable, abstract_args: tuple,
                    abstract_kwargs: Optional[dict] = None,
                    want_text: bool = False) -> dict:
    """Replay lower+compile on abstract shapes and extract the program
    bill. Returns a flat dict of JSON-ready fields — every one None
    where the API is missing — plus, with `want_text=True`, the
    compiled HLO text under `"hlo_text"` (for the comms scan; never
    emitted into metric streams — it is megabytes).

        lower_s / compile_s   cache-warm lowering/compile wall split
        flops                 cost_analysis "flops"
        bytes_accessed        cost_analysis "bytes accessed"
        argument_bytes / output_bytes / temp_bytes / peak_bytes
                              memory_analysis (peak derived; see above)

    Guarded end to end: any failure (no .lower, tracing error on a
    wrapper, backend refusal) yields the all-null record, never an
    exception into the training loop."""
    import time

    rec: dict = {"lower_s": None, "compile_s": None, "flops": None,
                 "bytes_accessed": None, "argument_bytes": None,
                 "output_bytes": None, "temp_bytes": None,
                 "peak_bytes": None}
    lower = getattr(fn, "lower", None)
    if not callable(lower):
        return rec
    try:
        t0 = time.perf_counter()
        lowered = lower(*abstract_args, **(abstract_kwargs or {}))
        t1 = time.perf_counter()
        compiled = lowered.compile()
        t2 = time.perf_counter()
    except Exception:
        return rec
    rec["lower_s"] = round(t1 - t0, 6)
    rec["compile_s"] = round(t2 - t1, 6)
    ca = guarded_cost_analysis(compiled)
    if ca:
        rec["flops"] = ca.get("flops")
        rec["bytes_accessed"] = ca.get("bytes accessed")
    ma = guarded_memory_analysis(compiled)
    if ma:
        for k in ("argument_bytes", "output_bytes", "temp_bytes",
                  "peak_bytes"):
            rec[k] = ma.get(k)
    if want_text:
        rec["hlo_text"] = guarded_compiled_text(compiled)
        # The executable's sharding pytrees ride along for the IR
        # audit's JIR003 fixed-point check (analysis/ir.py). NOT
        # JSON-serializable — consumers must strip them before any
        # metric stream (the watchdog pops them into the view store).
        rec["input_shardings"] = _guarded_attr(compiled,
                                               "input_shardings")
        rec["output_shardings"] = _guarded_attr(compiled,
                                                "output_shardings")
    return rec


def _guarded_attr(obj: Any, attr: str) -> Any:
    """`getattr` hardened against raising properties (the AOT sharding
    accessors vary across jax versions) — None on any failure, in the
    null-degrading discipline of the other guarded accessors."""
    try:
        return getattr(obj, attr, None)
    except Exception:
        return None
