"""Prometheus-style metrics exposition (stdlib text format, no deps).

Two surfaces, one renderer:

- **Serving**: `daemon_metrics(daemon)` renders the scoring daemon's
  state — request-latency histogram, per-model request/compile
  gauges, registry hits/misses/evictions/cold-starts (tombstone
  recoveries), circuit-breaker state, the sliding health window, tick
  fusion stats, the watchdog's `compile` / `compile_cached` counters,
  and the served-score drift monitors — as Prometheus text exposition
  format 0.0.4. `serve/daemon.serve_http` mounts it at `GET /metrics`.
- **Training**: `TextfileExporter` writes the same format to a
  `.prom` textfile in the run directory after every epoch (the
  node-exporter textfile-collector convention: scrape the file, not
  the trainer), installed process-wide via `install_exporter` — the
  same registry pattern as `utils.logging.install_timeline`, and the
  same contract: a no-op costing one `is None` check when absent, so
  the default training path is untouched.

The renderer is deliberately minimal: counters, gauges and one
fixed-bucket histogram; `# HELP` / `# TYPE` headers; label escaping per
the exposition-format spec. Values are whatever the daemon already
counts — this module computes nothing new on the hot path.
"""

from __future__ import annotations

import math
import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

#: the exposition-format content type /metrics answers with
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

PREFIX = "factorvae"


def _fmt(v) -> str:
    if v is None:
        return "NaN"
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape(v) -> str:
    return (str(v).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def metric_line(name: str, value, labels: Optional[dict] = None) -> str:
    lab = ""
    if labels:
        inner = ",".join(f'{k}="{_escape(v)}"'
                         for k, v in labels.items() if v is not None)
        if inner:
            lab = "{" + inner + "}"
    return f"{name}{lab} {_fmt(value)}"


class LatencyHistogram:
    """Fixed-bucket latency histogram (seconds). Thread-safe: observe
    comes from the serving loop, render from the HTTP handler.

    Trace exemplars (ISSUE 20): `observe(dt, trace_id=...)` remembers
    the LAST trace that landed in each bucket, and `render` emits one
    `# exemplar` comment line per annotated bucket right after the
    bucket's sample — so "what request was a p99?" is one grep from
    the scrape to `obs.trace --trace <id>`. Comment lines are legal
    exposition (every parser skips `#`), and the router's own
    histogram rides `merge_expositions(extra_families=...)` verbatim,
    so its exemplars survive the fleet merge."""

    DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                       0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

    def __init__(self, buckets: Sequence[float] = DEFAULT_BUCKETS):
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self._counts = [0] * (len(self.buckets) + 1)  # + the +Inf slot
        self._exemplars: List[Optional[Tuple[str, float]]] = \
            [None] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._n = 0
        self._lock = threading.Lock()

    def observe(self, seconds: float,
                trace_id: Optional[str] = None) -> None:
        s = float(seconds)
        with self._lock:
            i = len(self.buckets)
            for j, b in enumerate(self.buckets):
                if s <= b:
                    i = j
                    break
            self._counts[i] += 1
            if trace_id is not None:
                self._exemplars[i] = (str(trace_id), s)
            self._sum += s
            self._n += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._n

    def render(self, name: str, labels: Optional[dict] = None
               ) -> List[str]:
        with self._lock:
            counts = list(self._counts)
            exemplars = list(self._exemplars)
            total, n = self._sum, self._n
        lines = []
        cum = 0
        for b, c, ex in zip(self.buckets, counts, exemplars):
            cum += c
            lab = dict(labels or {})
            lab["le"] = _fmt(b)
            lines.append(metric_line(f"{name}_bucket", cum, lab))
            if ex is not None:
                tid, s = ex
                lines.append(f'# exemplar {name}_bucket '
                             f'le="{_fmt(b)}" trace_id="{_escape(tid)}" '
                             f'value={_fmt(round(s, 6))}')
        lab = dict(labels or {})
        lab["le"] = "+Inf"
        lines.append(metric_line(f"{name}_bucket", n, lab))
        if exemplars[-1] is not None:
            tid, s = exemplars[-1]
            lines.append(f'# exemplar {name}_bucket le="+Inf" '
                         f'trace_id="{_escape(tid)}" '
                         f'value={_fmt(round(s, 6))}')
        lines.append(metric_line(f"{name}_sum", total, labels))
        lines.append(metric_line(f"{name}_count", n, labels))
        return lines


def render_families(
        families: Sequence[Tuple[str, str, str, List[str]]]) -> str:
    """[(name, type, help, sample_lines)] -> exposition text (families
    with no samples are dropped — an absent metric beats a lying 0)."""
    out: List[str] = []
    for name, typ, help_, lines in families:
        if not lines:
            continue
        out.append(f"# HELP {name} {help_}")
        out.append(f"# TYPE {name} {typ}")
        out.extend(lines)
    return "\n".join(out) + "\n"


def inject_labels(sample: str, labels: dict) -> str:
    """One exposition sample line with extra labels spliced in —
    `name{a="b"} 1` or `name 1` gains every (k, v) of `labels`. The
    router's fleet scrape uses this to relabel each worker's families
    with its `worker_id` before merging them into one exposition."""
    inner = ",".join(f'{k}="{_escape(v)}"' for k, v in labels.items()
                     if v is not None)
    if not inner:
        return sample
    brace = sample.find("{")
    if brace != -1 and brace < sample.rfind("}"):
        close = sample.rfind("}")
        existing = sample[brace + 1:close].strip()
        sep = "," if existing else ""
        return (sample[:brace + 1] + inner + sep
                + sample[brace + 1:])
    name, _, rest = sample.partition(" ")
    return f"{name}{{{inner}}} {rest}"


def merge_expositions(
        parts: Sequence[Tuple[dict, str]],
        extra_families: Sequence[Tuple[str, str, str, List[str]]] = (),
) -> str:
    """Merge several exposition payloads into ONE valid exposition:
    `parts` is [(labels, text), ...] — every sample line of `text`
    gains `labels` (the fleet scrape's `worker_id`), and families that
    appear in several payloads collapse under one `# HELP`/`# TYPE`
    header (duplicate headers are invalid exposition). `extra_families`
    (the router's own counters) render FIRST. Sample order inside a
    family follows `parts` order, so one worker's histogram buckets
    stay contiguous."""
    from collections import OrderedDict

    merged: "OrderedDict[str, List]" = OrderedDict()
    for name, typ, help_, lines in extra_families:
        merged[name] = [typ, help_, list(lines)]
    for labels, text in parts:
        family = None
        for line in (text or "").splitlines():
            if line.startswith("# HELP "):
                rest = line[len("# HELP "):]
                name, _, help_ = rest.partition(" ")
                family = name
                merged.setdefault(name, ["untyped", help_, []])
                merged[name][1] = merged[name][1] or help_
            elif line.startswith("# TYPE "):
                rest = line[len("# TYPE "):]
                name, _, typ = rest.partition(" ")
                family = name
                merged.setdefault(name, [typ or "untyped", "", []])
                if typ:
                    merged[name][0] = typ
            elif line.startswith("#") or not line.strip():
                continue
            else:
                sample = inject_labels(line, labels)
                name = line.split("{", 1)[0].split(" ", 1)[0]
                if family is None or not (
                        name == family or name.startswith(family + "_")):
                    family = name
                    merged.setdefault(name, ["untyped", "", []])
                merged[family][2].append(sample)
    return render_families([(n, t, h, ls)
                            for n, (t, h, ls) in merged.items()])


def autoscale_families(
        signals: Dict) -> List[Tuple[str, str, str, List[str]]]:
    """The router's autoscaler input signals as exposition families
    (ISSUE 17): queue depth, observed p50/p99 against the declared SLO,
    and per-worker inflight under `worker_id` labels — the exact
    numbers the scaling loop decides from, exported so an operator can
    replay any scale decision off the scrape. Router `/metrics` merges
    these via `merge_expositions(extra_families=...)`; absent signals
    render as no samples (an absent metric beats a lying 0)."""
    p = f"{PREFIX}_router"
    fam: List[Tuple[str, str, str, List[str]]] = []
    for key, name, help_ in (
            ("queue_depth", f"{p}_queue_depth",
             "client requests queued/in flight at the router (the "
             "autoscaler's load signal)"),
            ("p50_ms", f"{p}_observed_p50_ms",
             "median client-request latency over the router's sliding "
             "window"),
            ("p99_ms", f"{p}_observed_p99_ms",
             "p99 client-request latency over the router's sliding "
             "window (compared against the declared SLO)"),
            ("slo_ms", f"{p}_slo_ms",
             "declared latency SLO the autoscaler defends (0 = none "
             "declared)"),
            ("workers_healthy", f"{p}_autoscale_workers_healthy",
             "healthy workers the autoscaler can spread load over"),
            ("workers_total", f"{p}_autoscale_workers_total",
             "pool worker slots, healthy or not")):
        v = signals.get(key)
        fam.append((name, "gauge", help_,
                    [] if v is None else [metric_line(name, v)]))
    inflight = signals.get("worker_inflight") or {}
    fam.append((f"{p}_worker_inflight", "gauge",
                "forwards currently in flight per worker",
                [metric_line(f"{p}_worker_inflight", v,
                             {"worker_id": wid})
                 for wid, v in sorted(inflight.items())]))
    return fam


# ---------------------------------------------------------------------------
# serving-side exposition
# ---------------------------------------------------------------------------

_HEALTH_CODE = {"ok": 0, "degraded": 1, "failing": 2, "draining": 3}


def daemon_metrics(daemon) -> str:
    """The scoring daemon's full /metrics payload (see module
    docstring). Reads daemon/registry/watchdog counters only — one
    scrape does zero scoring work. Holds the daemon's tick lock for
    the whole render: every counter in one exposition comes from the
    same instant, never half-way through a tick (the scrape-vs-tick
    interleaving graftlint JGL009 exists to catch). Lock order inside
    matches the tick path's: daemon -> registry/drift -> logger."""
    from factorvae_tpu.obs.watchdog import compile_event_counts

    with daemon._lock:
        return _render_daemon_metrics(daemon, compile_event_counts)


def _render_daemon_metrics(daemon, compile_event_counts) -> str:
    p = PREFIX
    reg = daemon.registry.stats()
    health = daemon.health()
    fam: List[Tuple[str, str, str, List[str]]] = []

    fam.append((f"{p}_serve_requests_total", "counter",
                "scoring requests answered ok",
                [metric_line(f"{p}_serve_requests_total",
                             daemon.requests_served)]))
    fam.append((f"{p}_serve_ticks_total", "counter",
                "dispatch ticks handled",
                [metric_line(f"{p}_serve_ticks_total", daemon.ticks)]))
    fam.append((f"{p}_serve_dispatches_total", "counter",
                "scoring program dispatches (fused groups count once)",
                [metric_line(f"{p}_serve_dispatches_total",
                             daemon.dispatches)]))
    fam.append((f"{p}_serve_fused_requests_total", "counter",
                "requests answered through a fused multi-model dispatch",
                [metric_line(f"{p}_serve_fused_requests_total",
                             daemon.fused_requests)]))
    fam.append((f"{p}_serve_deadline_misses_total", "counter",
                "requests whose scores landed past their deadline",
                [metric_line(f"{p}_serve_deadline_misses_total",
                             daemon.deadline_misses)]))
    fam.append((f"{p}_serve_breaker_fast_fails_total", "counter",
                "requests fast-failed by an open circuit breaker",
                [metric_line(f"{p}_serve_breaker_fast_fails_total",
                             daemon.breaker_fast_fails)]))
    fam.append((f"{p}_serve_request_latency_seconds", "histogram",
                "tick arrival to scores landing, per scoring request",
                daemon.latency.render(
                    f"{p}_serve_request_latency_seconds")))

    # health window: status code, error rate, window fill
    fam.append((f"{p}_serve_health_status", "gauge",
                "0=ok 1=degraded 2=failing 3=draining",
                [metric_line(f"{p}_serve_health_status",
                             _HEALTH_CODE.get(health["status"], 2))]))
    fam.append((f"{p}_serve_health_error_rate", "gauge",
                "error rate over the sliding outcome window",
                [metric_line(f"{p}_serve_health_error_rate",
                             health["error_rate"])]))
    fam.append((f"{p}_serve_health_window", "gauge",
                "scoring outcomes currently in the health window",
                [metric_line(f"{p}_serve_health_window",
                             health["window"])]))

    # registry totals (cold_starts == tombstone recoveries)
    fam.append((f"{p}_registry_models", "gauge",
                "models currently resident",
                [metric_line(f"{p}_registry_models", reg["models"])]))
    fam.append((f"{p}_registry_bytes", "gauge",
                "resident parameter bytes",
                [metric_line(f"{p}_registry_bytes", reg["bytes"])]))
    for key, help_ in (("hits", "registry lookup hits"),
                       ("misses", "registry lookup misses"),
                       ("evictions", "LRU evictions"),
                       ("cold_starts",
                        "tombstone recoveries (evicted models reloaded "
                        "from their source)")):
        fam.append((f"{p}_registry_{key}_total", "counter", help_,
                    [metric_line(f"{p}_registry_{key}_total",
                                 reg[key])]))

    # per-model gauges
    req_lines, warm_lines, breaker_lines, fails_lines = [], [], [], []
    for e in reg["entries"]:
        lab = {"model": e["key"], "alias": e["alias"],
               "precision": e["precision"]}
        req_lines.append(metric_line(
            f"{p}_model_requests_total", e["requests"], lab))
        warm_lines.append(metric_line(
            f"{p}_model_compiled", int(bool(e["compiled"])), lab))
    for key, b in sorted(daemon.breaker_states().items()):
        lab = {"model": key}
        breaker_lines.append(metric_line(
            f"{p}_breaker_open", int(b["open"]), lab))
        fails_lines.append(metric_line(
            f"{p}_breaker_consecutive_fails", b["fails"], lab))
    fam.append((f"{p}_model_requests_total", "counter",
                "requests served per resident model", req_lines))
    fam.append((f"{p}_model_compiled", "gauge",
                "1 when the model's serial scoring program is warm",
                warm_lines))
    fam.append((f"{p}_breaker_open", "gauge",
                "1 while the model's circuit breaker is open",
                breaker_lines))
    fam.append((f"{p}_breaker_consecutive_fails", "gauge",
                "consecutive failures feeding the breaker",
                fails_lines))

    # compile taxonomy (watchdog counters; the warm-restart contract:
    # a restarted daemon with a persistent cache scrapes compile==0,
    # compile_cached>0)
    cc = compile_event_counts()
    fam.append((f"{p}_compile_total", "counter",
                "watched-jit cache misses by taxonomy (compile=built, "
                "compile_cached=deserialized from the persistent cache)",
                [metric_line(f"{p}_compile_total", cc["compile"],
                             {"kind": "compile"}),
                 metric_line(f"{p}_compile_total", cc["compile_cached"],
                             {"kind": "compile_cached"})]))

    # served-score drift (+ per-model thresholds and drift state —
    # walk-forward promotion policy, ISSUE 14)
    corr_lines, drift_lines, day_lines = [], [], []
    thr_lines, drifting_lines = [], []
    for model, st in daemon.drift.stats().items():
        lab = {"model": model}
        if st["last_rank_corr"] is not None:
            corr_lines.append(metric_line(
                f"{p}_score_rank_corr_prev_day", st["last_rank_corr"],
                lab))
        drift_lines.append(metric_line(
            f"{p}_score_drift_total", st["drift_events"], lab))
        day_lines.append(metric_line(
            f"{p}_score_days_digested", st["days_digested"], lab))
        thr_lines.append(metric_line(
            f"{p}_score_drift_threshold", st["threshold"], lab))
        drifting_lines.append(metric_line(
            f"{p}_score_drifting", int(bool(st["drifting"])), lab))
    fam.append((f"{p}_score_rank_corr_prev_day", "gauge",
                "rank correlation of the served cross-section vs the "
                "model's previously served day", corr_lines))
    fam.append((f"{p}_score_drift_total", "counter",
                "day-over-day rank-correlation collapses below the "
                "drift threshold", drift_lines))
    fam.append((f"{p}_score_days_digested", "gauge",
                "distinct days with a served-score digest", day_lines))
    fam.append((f"{p}_score_drift_threshold", "gauge",
                "ACTIVE drift threshold per model (per-model override "
                "or the daemon-wide default)", thr_lines))
    fam.append((f"{p}_score_drifting", "gauge",
                "1 while the model's latest day-over-day rank "
                "correlation sits below its active threshold",
                drifting_lines))
    return render_families(fam)


# ---------------------------------------------------------------------------
# trainer-side textfile exporter
# ---------------------------------------------------------------------------

#: epoch-record keys exported as gauges when present (probe keys ride
#: along automatically — anything numeric and not in the skip set goes)
_EPOCH_SKIP = {"epoch", "step"}


class TextfileExporter:
    """Write one epoch's metrics as a Prometheus textfile (the
    node-exporter textfile-collector convention). The write is atomic
    (tmp + rename) so a scraper never reads a torn exposition."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        self.epochs = 0

    @staticmethod
    def _lanes(v) -> List[Tuple[Optional[int], float]]:
        """Numeric lanes of an epoch-record value: scalars are one
        unlabeled lane; fleet per-seed lists get a seed_lane label."""
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            return [(None, float(v))]
        if isinstance(v, list):
            return [(i, float(x)) for i, x in enumerate(v)
                    if isinstance(x, (int, float))
                    and not isinstance(x, bool)]
        return []

    def export_epoch(self, rec: Dict) -> None:
        self.epochs += 1
        p = PREFIX
        fam: List[Tuple[str, str, str, List[str]]] = [
            (f"{p}_train_epochs_total", "counter",
             "epochs exported this run",
             [metric_line(f"{p}_train_epochs_total", self.epochs)]),
        ]
        if isinstance(rec.get("epoch"), (int, float)):
            fam.append((f"{p}_train_epoch", "gauge",
                        "most recent epoch number",
                        [metric_line(f"{p}_train_epoch",
                                     rec["epoch"])]))
        if isinstance(rec.get("step"), (int, float)):
            fam.append((f"{p}_train_step", "gauge",
                        "optimizer step after the epoch",
                        [metric_line(f"{p}_train_step", rec["step"])]))
        # Fleet lane-config labels (ISSUE 12): hyper lanes race
        # DIFFERENT configs, so every per-lane gauge carries the config
        # that produced it (lr/kl_weight/config hash) next to its
        # seed_lane index — the scrape-side twin of the obs.report flag
        # labels. Absent on serial runs and pre-ISSUE-12 streams.
        lane_names = rec.get("lane_labels")
        if not (isinstance(lane_names, list)
                and all(isinstance(x, str) for x in lane_names)):
            lane_names = None

        def _labels(lane):
            if lane is None:
                return None
            lab = {"seed_lane": str(lane)}
            if lane_names and lane < len(lane_names):
                lab["lane_config"] = lane_names[lane]
            return lab

        for key in sorted(rec):
            if key in _EPOCH_SKIP or key.startswith("_"):
                continue
            lanes = self._lanes(rec[key])
            if not lanes:
                continue
            name = f"{p}_train_{key}"
            lines = [metric_line(name, v, _labels(lane))
                     for lane, v in lanes]
            fam.append((name, "gauge",
                        f"epoch-record metric '{key}'", lines))
        text = render_families(fam)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as fh:
            fh.write(text)
        os.replace(tmp, self.path)


# Module-level registry, mirroring utils.logging.install_timeline: the
# epoch loops call `export_epoch_metrics(rec)` unconditionally; without
# an installed exporter that is one `is None` check.
_EXPORTER: Optional[TextfileExporter] = None


def install_exporter(exp: Optional[TextfileExporter]
                     ) -> Optional[TextfileExporter]:
    """Install the process-wide textfile exporter; returns the previous
    one so callers (tests, the CLI's finally block) can restore it."""
    global _EXPORTER
    prev = _EXPORTER
    _EXPORTER = exp
    return prev


def current_exporter() -> Optional[TextfileExporter]:
    return _EXPORTER


def export_epoch_metrics(rec: Dict) -> None:
    exp = _EXPORTER
    if exp is not None:
        exp.export_epoch(rec)
