"""Pillar 5: the streaming run monitor — obs.report, while the run flies.

    python -m factorvae_tpu.obs.live RUN.jsonl --follow [--json]
        [--poll 0.2] [--idle-timeout S]
        [--spike-mult 10] [--slow-frac 0.5] [--diverge-frac 0.2]
        [--diverge-epochs 3]

Every other obs surface reads a FINISHED stream. This one tail-follows
an in-flight RUN.jsonl — torn-line tolerant: a partially-written final
line (the async writer mid-record) is buffered, never parsed, and
emits exactly once when the writer completes it — and feeds the records
into the SAME flag logic `obs.report` uses (`build_report`: nonfinite,
grad_spike, val_divergence, slow_epoch, compile_storm, budget breaches,
recovery flags, score_drift), emitting an alert as each flag appears.

**Consistency pin** (tests/test_live.py): the monitor's final flag set
over an in-flight stream is IDENTICAL — same flags, same record
identities (`line`), same details — to `obs.report` run post-hoc on the
completed stream, because both run `build_report` over identically
parsed record lists. There is no second flag implementation to drift.

The retrospective checks (medians, divergence baselines) are honest
about being retrospective: a flag raised early can dissolve as later
records move the baseline (a slow-looking epoch 1 stops being slow once
the run median settles). The alert stream says so — a dissolved flag
emits a `resolved` alert — rather than pinning live semantics to a
weaker "first N records" judgment that post-hoc reports would then
contradict.

`obs.timeline --follow` and `obs.report --follow` delegate here, so one
CLI covers in-flight and finished runs.
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable, Iterator, List, Optional, Tuple

from factorvae_tpu.obs.timeline import RunStreamError

#: record routing shared with obs.timeline.load_run — one taxonomy,
#: never two (the consistency pin depends on it)
_EPOCH_EVENTS = ("epoch", "fleet_epoch")


class LiveRun:
    """Incremental accumulator with exactly `load_run`'s shape:
    {"spans", "marks", "epochs", "meta", "events"} plus `_stats`. Feed
    it raw lines in stream order and `run` stays what `load_run` would
    have parsed from the same prefix."""

    def __init__(self) -> None:
        self.run: dict = {"spans": [], "marks": [], "epochs": [],
                          "meta": [], "events": []}
        self.lines = 0      # physical lines seen (torn tail excluded)
        self.bad = 0
        self.records = 0

    def add_line(self, index: int, line: str) -> Optional[dict]:
        """Route one COMPLETE physical line (same skip/annotate rules as
        load_run); returns the parsed record or None."""
        text = line.strip()
        if not text:
            return None
        self.lines += 1
        try:
            rec = json.loads(text)
        except ValueError:
            self.bad += 1
            return None
        if not isinstance(rec, dict):
            self.bad += 1
            return None
        rec.setdefault("_line", index)
        ev = rec.get("event")
        if ev == "span":
            self.run["spans"].append(rec)
        elif ev == "mark":
            self.run["marks"].append(rec)
        elif ev in _EPOCH_EVENTS:
            self.run["epochs"].append(rec)
        elif ev == "run_meta":
            self.run["meta"].append(rec)
        else:
            self.run["events"].append(rec)
        self.records += 1
        return rec


def iter_lines(path: str, follow: bool = True, poll_s: float = 0.2,
               idle_timeout: Optional[float] = None,
               stop: Optional[Callable[[], bool]] = None,
               wait_for_file: bool = True,
               ) -> Iterator[Tuple[int, str]]:
    """Yield (physical_line_index, text) for each COMPLETE line of a
    growing JSONL file. The torn-line contract: bytes after the last
    newline stay buffered — a half-written record is never yielded, and
    yields exactly once when its newline lands. `follow=False` drains
    what exists and returns (the buffered tail, if any, is dropped
    exactly like `load_run`'s last_bad skip when it isn't valid yet —
    callers wanting finished-stream semantics use open_run instead).

    Under `follow=True` the generator polls for growth every `poll_s`
    and ends when `stop()` turns true or `idle_timeout` seconds pass
    with no new bytes (None = follow forever)."""
    deadline = None
    while not os.path.exists(path):
        if not follow or not wait_for_file:
            raise RunStreamError(f"cannot read {path}: no such file")
        if stop is not None and stop():
            return
        if deadline is None and idle_timeout is not None:
            deadline = time.perf_counter() + idle_timeout
        if deadline is not None and time.perf_counter() > deadline:
            return
        time.sleep(poll_s)
    buf = b""
    index = 0
    stopping = False
    idle_since = time.perf_counter()
    with open(path, "rb") as fh:
        while True:
            chunk = fh.read(65536)
            if chunk:
                idle_since = time.perf_counter()
                buf += chunk
                while b"\n" in buf:
                    raw, buf = buf.split(b"\n", 1)
                    yield index, raw.decode("utf-8", errors="replace")
                    index += 1
                continue
            if stopping or not follow:
                return
            if stop is not None and stop():
                # one more read pass before returning: bytes the writer
                # appended between our empty read and the stop signal
                # must not be lost to that race
                stopping = True
                continue
            if idle_timeout is not None and \
                    time.perf_counter() - idle_since > idle_timeout:
                return
            time.sleep(poll_s)


def tail_bytes(path: str, since: int = 0,
               max_bytes: int = 4 << 20) -> Tuple[bytes, int]:
    """Server side of ``GET /runstream?since=<offset>`` (pillar 6,
    obs/collect.py): the byte range [since, next) of a growing JSONL
    file, cut at the LAST newline so a torn final line — the writer
    mid-record — is never served; the client re-requests from ``next``
    and receives that line exactly once, complete. The same contract
    `iter_lines` keeps locally, spoken over HTTP. Returns (payload,
    next_offset); missing file or out-of-range offset yields an empty
    payload with a resynced offset (streams are append-only, so a
    too-large `since` only happens against a recreated file)."""
    since = max(0, int(since))
    try:
        with open(path, "rb") as fh:
            fh.seek(0, os.SEEK_END)
            size = fh.tell()
            if since >= size:
                return b"", min(since, size)
            fh.seek(since)
            chunk = fh.read(max_bytes)
    except OSError:
        return b"", 0
    cut = chunk.rfind(b"\n") + 1
    return chunk[:cut], since + cut


class LiveMonitor:
    """Flag state over a live stream. `update()` recomputes the full
    `obs.report` flag set over everything seen so far — the SAME
    `build_report` the post-hoc CLI runs, so the current set is always
    exactly what a report over the accumulated prefix would say — and
    diffs it against the previous set, returning (new, resolved) alert
    lists. Flag identity is (flag, line, epoch, ordinal): the stream
    position pins the record in concatenated multi-run streams where
    epoch numbers repeat, and the ordinal keeps DISTINCT same-kind
    flags on one record distinct (a record with a NaN loss AND a
    nonfinite probe counter is two flags; two spiking seed lanes on
    one fleet record are two flags) — while keeping the identity
    stable across recomputes whose detail strings move with the
    baselines (a shifting run median must not churn new/resolved
    pairs)."""

    def __init__(self, **report_kw) -> None:
        self.acc = LiveRun()
        self.report_kw = report_kw
        self._current: dict = {}   # identity -> flag dict
        self.last_report: Optional[dict] = None

    def add_line(self, index: int, line: str) -> Optional[dict]:
        return self.acc.add_line(index, line)

    def flags(self) -> List[dict]:
        from factorvae_tpu.obs.report import build_report

        self.last_report = build_report(self.acc.run, **self.report_kw)
        return self.last_report["flags"]

    def update(self) -> Tuple[List[dict], List[dict]]:
        now: dict = {}
        counts: dict = {}
        for f in self.flags():
            base = (f.get("flag"), f.get("line"), f.get("epoch"))
            n = counts.get(base, 0)
            counts[base] = n + 1
            now[base + (n,)] = f
        new = [f for k, f in now.items() if k not in self._current]
        resolved = [f for k, f in self._current.items() if k not in now]
        self._current = now
        return new, resolved

    def current_flags(self) -> List[dict]:
        return list(self._current.values())


def follow_run(path: str, follow: bool = True, poll_s: float = 0.2,
               idle_timeout: Optional[float] = None,
               stop: Optional[Callable[[], bool]] = None,
               on_alert: Optional[Callable[[str, dict], None]] = None,
               update_every: int = 1,
               update_interval_s: float = 0.5,
               **report_kw) -> LiveMonitor:
    """Drive a LiveMonitor over `path`: drain complete lines, recompute
    flags when `update_every` records have arrived AND at least
    `update_interval_s` passed since the last recompute (and always
    once at the end), calling `on_alert(status, flag)` with status
    "new" / "resolved" as the flag set changes. The time throttle is
    what keeps a long follow linear: each recompute replays
    `build_report` over the whole accumulated run, so per-record
    recomputation over a high-rate stream (a serving daemon's request
    spans) would grow quadratic and fall behind the writer; at most
    ~2 recomputes/second the steady-state cost stays bounded while
    the end-of-stream state — the consistency pin — is untouched.
    `update_interval_s=0` disables the throttle (tests). Returns the
    monitor (its `current_flags()` after a completed stream equals
    the post-hoc report's flags)."""
    mon = LiveMonitor(**report_kw)
    pending = 0
    last_update = float("-inf")

    def emit_update() -> None:
        new, resolved = mon.update()
        if on_alert is not None:
            for f in new:
                on_alert("new", f)
            for f in resolved:
                on_alert("resolved", f)

    for index, line in iter_lines(path, follow=follow, poll_s=poll_s,
                                  idle_timeout=idle_timeout, stop=stop):
        if mon.add_line(index, line) is None:
            continue
        pending += 1
        if pending >= max(1, update_every) \
                and time.perf_counter() - last_update >= update_interval_s:
            pending = 0
            emit_update()
            last_update = time.perf_counter()
    emit_update()
    return mon


def main(argv: Optional[list] = None) -> int:
    import argparse
    import sys

    ap = argparse.ArgumentParser(
        prog="python -m factorvae_tpu.obs.live",
        description="Streaming run monitor: obs.report's flags emitted "
                    "as alerts while the RUN.jsonl is still being "
                    "written (pillar 5, docs/observability.md)")
    ap.add_argument("run_jsonl")
    ap.add_argument("--follow", action="store_true",
                    help="keep tailing for new records (default: drain "
                         "the stream once and exit)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable alert stream (one JSON "
                         "object per alert + a final summary)")
    ap.add_argument("--poll", type=float, default=0.2,
                    help="tail poll interval, seconds")
    ap.add_argument("--idle-timeout", type=float, default=None,
                    help="stop following after this many seconds "
                         "without new bytes (default: follow forever)")
    ap.add_argument("--spike-mult", type=float, default=10.0)
    ap.add_argument("--slow-frac", type=float, default=0.5)
    ap.add_argument("--diverge-frac", type=float, default=0.2)
    ap.add_argument("--diverge-epochs", type=int, default=3)
    args = ap.parse_args(argv)

    def emit(status: str, f: dict) -> None:
        if args.json:
            print(json.dumps({"event": "alert", "status": status, **f}),
                  flush=True)
        else:
            where = (f"epoch {f['epoch']}" if f.get("epoch") is not None
                     else "program")
            tag = "ALERT" if status == "new" else "RESOLVED"
            print(f"{tag} {where}: [{f['flag']}] {f['detail']}",
                  flush=True)

    try:
        mon = follow_run(
            args.run_jsonl, follow=args.follow, poll_s=args.poll,
            idle_timeout=args.idle_timeout, on_alert=emit,
            spike_mult=args.spike_mult, slow_frac=args.slow_frac,
            diverge_frac=args.diverge_frac,
            diverge_epochs=args.diverge_epochs)
    except RunStreamError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    except KeyboardInterrupt:
        print("", file=sys.stderr)
        return 130
    flags = mon.current_flags()
    counts: dict = {}
    for f in flags:
        counts[f["flag"]] = counts.get(f["flag"], 0) + 1
    if args.json:
        print(json.dumps({
            "event": "summary", "records": mon.acc.records,
            "lines": mon.acc.lines, "bad_lines": mon.acc.bad,
            "flags": len(flags), "flag_counts": counts,
        }))
    else:
        if counts:
            print("current flags: " + ", ".join(
                f"{k} x{n}" for k, n in sorted(counts.items())))
        else:
            print(f"no health flags over {mon.acc.records} record(s)")
    if mon.acc.lines == 0:
        print(f"error: {args.run_jsonl} is empty — no run has written "
              "to this stream yet", file=sys.stderr)
        return 2
    if mon.acc.bad == mon.acc.lines:
        print(f"error: {args.run_jsonl} is not a JSONL metric stream "
              f"(none of its {mon.acc.lines} lines parse)",
              file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
