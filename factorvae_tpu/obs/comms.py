"""Static collective-communication scan of compiled HLO text.

"One sharding story" (PR 6) is only steerable if each composed
(data, stock, S) cell reports its communication bill, not just
windows/sec. This module reads the POST-OPTIMIZATION (post-SPMD-
partitioning) HLO text of a compiled program — `obs/compile.
guarded_compiled_text` — and statically accounts its collective ops:

- **Which collectives** XLA inserted (all-reduce, all-gather,
  reduce-scatter, collective-permute, all-to-all; async `-start` forms
  counted once, `-done` halves skipped).
- **Payload bytes per op** from the result shape (dtype size x element
  count; tuple shapes summed). These are PAYLOAD bytes — what the
  program hands the collective — not wire bytes (an all-reduce moves
  ~2(k-1)/k of its payload per device on a ring); payload is the number
  a budget can be written against without modeling the interconnect.
- **Mesh-axis attribution**: an op's replica groups (explicit
  `{{0,1},{2,3}}` and iota `[2,2]<=[4]` / `<=[2,2]T(1,0)` forms both
  parsed) are matched against the groups each mesh axis would form —
  a gradient all-reduce rides 'data', the masked-softmax reductions
  ride 'stock', anything else is 'mixed'.
- **Loop placement**: collectives reachable from a `while` body (the
  epoch scan) run once per step; the rest once per program. The
  summary multiplies accordingly, so `bytes_per_epoch` is
  steps x per-step payload + once-per-program payload.

Degenerate groups (size <= 1 — the serial anchor, the 1x1 mesh) are
dropped: no communication happens, so the serial cell's comms block is
honestly zero. Pure text analysis — nothing here touches the program
or its numbers.
"""

from __future__ import annotations

import re
from typing import List, Optional

__all__ = ["comms_block", "parse_replica_groups", "scan_collectives"]

COLLECTIVE_KINDS = (
    "all-reduce-scatter",  # not a real HLO op; kept before the prefixes
    "reduce-scatter",
    "all-reduce",
    "all-gather",
    "all-to-all",
    "collective-permute",
)

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16, "f8e4m3fn": 1, "f8e5m2": 1,
}

# `%name = <result shape> <kind>[-start](operands...)`. The shape
# segment is matched lazily up to a WHITESPACE-preceded kind token so
# TPU tiled-layout annotations — `f32[128,256]{1,0:T(8,128)}`, memory
# spaces `S(1)` — parse too (a restricted character class silently
# missed every real-chip collective). Operand REFERENCES to ops named
# `%all-reduce.N` never match: the kind must be followed directly by
# `(` (or `-start(`), which only the defining position has.
_OP_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%[\w.\-]+\s*=\s*(?P<shape>\S.*?)\s"
    r"(?P<kind>" + "|".join(re.escape(k) for k in COLLECTIVE_KINDS)
    + r")(?P<start>-start)?\(")

_SHAPE_RE = re.compile(r"(?P<dtype>[a-z]\w*)\[(?P<dims>[\d,]*)\]")

_EXPLICIT_GROUPS_RE = re.compile(r"replica_groups=\{(?P<body>[\d{},\s]*)\}")
_IOTA_GROUPS_RE = re.compile(
    r"replica_groups=\[(?P<gshape>\d+,\d+)\]<=\[(?P<dims>[\d,]+)\]"
    r"(?:T\((?P<perm>[\d,]+)\))?")
_PAIRS_RE = re.compile(r"source_target_pairs=\{(?P<body>[\d{},\s]*)\}")

# Computation definitions start at column 0 and end with a bare '}'.
_COMP_DEF_RE = re.compile(r"^(?:ENTRY\s+)?%?(?P<name>[\w.\-]+)\s*(?:\(|\s)")
_COMP_REF_RE = re.compile(
    r"(?:to_apply|body|condition|calls)=\{?%?(?P<name>[\w.\-]+)")
_BODY_REF_RE = re.compile(r"body=%?(?P<name>[\w.\-]+)")


def _ints(s: str) -> List[int]:
    return [int(x) for x in re.findall(r"\d+", s)]


def parse_replica_groups(line: str) -> Optional[List[List[int]]]:
    """Replica groups of one HLO op line, explicit or iota form;
    collective-permute's source_target_pairs parse as 2-element groups.
    None when the line carries no group annotation (e.g.
    `replica_groups={}` = one group of all devices — the caller decides
    what "all" means)."""
    m = _IOTA_GROUPS_RE.search(line)
    if m:
        import numpy as np

        g, s = _ints(m.group("gshape"))
        dims = _ints(m.group("dims"))
        ids = np.arange(int(np.prod(dims))).reshape(dims)
        if m.group("perm"):
            ids = np.transpose(ids, _ints(m.group("perm")))
        return [list(map(int, row)) for row in ids.reshape(g, s)]
    m = _EXPLICIT_GROUPS_RE.search(line)
    if m:
        body = m.group("body").strip()
        if not body:
            return None  # replica_groups={}: one group of everything
        return [_ints(grp) for grp in re.findall(r"\{([\d,\s]*)\}", body)]
    m = _PAIRS_RE.search(line)
    if m:
        return [_ints(grp) for grp in
                re.findall(r"\{([\d,\s]*)\}", m.group("body"))]
    return None


def _shape_bytes(shape: str, async_start: bool = False) -> int:
    """Payload bytes of an HLO result-shape string (unknown dtypes
    counted at 4 bytes — wrong by a small factor beats silently
    dropped). Plain tuples sum their members; an async `-start` op's
    tuple ALIASES its input next to its output (`(f32[8,..], f32[32,..])
    all-gather-start`), so summing would double-count — the LARGEST
    top-level component (the output) is the payload there."""

    def arrays_bytes(s: str) -> int:
        total = 0
        for m in _SHAPE_RE.finditer(s):
            elems = 1
            for d in _ints(m.group("dims")):
                elems *= d
            total += _DTYPE_BYTES.get(m.group("dtype"), 4) * elems
        return total

    shape = shape.strip()
    if not (async_start and shape.startswith("(")):
        return arrays_bytes(shape)
    # split the tuple at depth-1 commas into top-level components
    parts, depth, cur = [], 0, []
    for ch in shape[1:-1] if shape.endswith(")") else shape[1:]:
        if ch in "([{":
            depth += 1
        elif ch in ")]}":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        parts.append("".join(cur))
    return max((arrays_bytes(p) for p in parts), default=0)


def _computation_blocks(text: str) -> dict:
    """name -> list of that computation's lines (HLO text layout:
    definitions start at column 0, close with a bare '}')."""
    blocks: dict = {}
    cur = None
    for line in text.splitlines():
        if line and not line[0].isspace() and line.rstrip().endswith("{"):
            # _COMP_DEF_RE strips the optional ENTRY prefix itself; a
            # character-set lstrip would mangle un-sigiled names that
            # happen to start with E/N/T/R/Y.
            m = _COMP_DEF_RE.match(line.strip())
            cur = m.group("name") if m else None
            if cur is not None:
                blocks[cur] = []
            continue
        if line.strip() == "}":
            cur = None
            continue
        if cur is not None:
            blocks[cur].append(line)
    return blocks


def _loop_computations(blocks: dict) -> set:
    """Names of computations reachable from any `while` body — their
    ops execute once per loop step (the epoch scan)."""
    bodies = set()
    refs: dict = {}
    for name, lines in blocks.items():
        refs[name] = set()
        for line in lines:
            for m in _COMP_REF_RE.finditer(line):
                refs[name].add(m.group("name"))
            for m in _BODY_REF_RE.finditer(line):
                bodies.add(m.group("name"))
    reach = set()
    frontier = list(bodies)
    while frontier:
        n = frontier.pop()
        if n in reach:
            continue
        reach.add(n)
        frontier.extend(refs.get(n, ()))
    return reach


def _axis_groups(mesh) -> dict:
    """Mesh axis name -> the set of participant-index groups a
    collective over ONLY that axis would form. Post-SPMD replica groups
    index the DEVICE ASSIGNMENT (the mesh's flattened device order),
    not `Device.id` — on a real TPU slice `mesh_utils` reorders devices
    for topology, so position != id and an id-based match would
    misattribute every op to 'mixed' exactly on the rig this scan
    exists for. Indices are therefore positions in the flattened
    `mesh.devices` array."""
    import numpy as np

    ids = np.arange(int(np.prod(mesh.devices.shape))).reshape(
        mesh.devices.shape)
    out = {}
    for i, name in enumerate(mesh.axis_names):
        moved = np.moveaxis(ids, i, -1).reshape(-1, ids.shape[i])
        out[str(name)] = frozenset(
            frozenset(int(x) for x in row) for row in moved)
    return out


def scan_collectives(hlo_text: str, mesh=None) -> List[dict]:
    """Per-op records for every communicating collective in the text:
    {kind, bytes, group_size, groups, axis, in_loop}. Degenerate ops
    (every group a single device) are dropped."""
    blocks = _computation_blocks(hlo_text)
    loops = _loop_computations(blocks)
    axes = _axis_groups(mesh) if mesh is not None else {}
    n_devices = (int(mesh.devices.size) if mesh is not None else None)
    ops = []
    for comp, lines in blocks.items():
        in_loop = comp in loops
        for line in lines:
            m = _OP_RE.match(line)
            if m is None:
                continue
            groups = parse_replica_groups(line)
            if groups is None:
                # replica_groups={} / no annotation: one group of all
                # participating devices.
                groups = ([list(range(n_devices))]
                          if n_devices else [[0, 1]])
            size = max((len(g) for g in groups), default=0)
            if size <= 1:
                continue  # no communication (the serial anchor)
            gset = frozenset(frozenset(g) for g in groups)
            axis = "mixed"
            for name, expect in axes.items():
                if gset == expect:
                    axis = name
                    break
            ops.append({
                "kind": m.group("kind"),
                "bytes": _shape_bytes(m.group("shape"),
                                      async_start=bool(m.group("start"))),
                "group_size": size,
                "groups": [sorted(g) for g in groups],
                "axis": axis,
                "in_loop": in_loop,
            })
    return ops


def comms_block(hlo_text: Optional[str], mesh=None,
                steps_per_epoch: int = 1) -> Optional[dict]:
    """The per-program comms bill as one JSON-ready block (what every
    `bench.py --mesh` cell carries). None in on a version-skewed jax
    (no compiled text) -> None out, never a crash."""
    if not hlo_text:
        return None
    ops = scan_collectives(hlo_text, mesh=mesh)
    by_kind: dict = {}
    by_axis: dict = {}
    per_program = 0
    per_epoch = 0
    for op in ops:
        by_kind[op["kind"]] = by_kind.get(op["kind"], 0) + 1
        mult = steps_per_epoch if op["in_loop"] else 1
        per_program += op["bytes"]
        per_epoch += op["bytes"] * mult
        by_axis[op["axis"]] = by_axis.get(op["axis"], 0) + op["bytes"] * mult
    return {
        "collective_ops": len(ops),
        "ops_by_kind": by_kind,
        "payload_bytes_per_program": per_program,
        "bytes_per_epoch": per_epoch,
        "bytes_by_axis": by_axis,
        "steps_per_epoch": steps_per_epoch,
    }
