"""Served-score drift monitors (the bridge to ROADMAP item 4).

A FactorVAE-style cross-sectional factor model degrades under regime
shift the quiet way: the daemon keeps answering 200s while the served
ranking decays (the Rank-IC drift E2EAI's end-to-end framing warns
about, PAPERS.md). This module watches the SERVED scores themselves:

- **Per-(model, day) distribution digests** — count/mean/std/quantiles
  of the cross-section the daemon actually answered with, computed once
  per (model, day) (repeat requests for a scored day are free) and
  logged as `score_digest` timeline marks.
- **Day-over-day rank correlation** — Spearman correlation between a
  model's served cross-section and the PREVIOUS day it served, paired
  by instrument. A healthy factor model's ranking churns slowly; a
  correlation collapse is the regime-shift signature. Below
  `threshold` (with at least `min_overlap` paired names) the monitor
  emits a `score_drift` mark, which `obs.report` / `obs.live` raise as
  a `score_drift` flag and `/metrics` exposes per model.

Host-side numpy only — the scoring programs, and the scores they
produce, are untouched (ISSUE 10's bitwise discipline). Without a
timeline installed the digests still accumulate for `/metrics`; the
marks are simply not recorded anywhere.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

from factorvae_tpu.utils.logging import timeline_event

#: mark names this monitor emits (obs.report keys its flag on the
#: second one; obs/report.DRIFT_MARK_FLAGS references it)
DIGEST_MARK = "score_digest"
DRIFT_MARK = "score_drift"

_QUANTILES = (0.05, 0.25, 0.5, 0.75, 0.95)


def score_digest(scores: np.ndarray) -> dict:
    """Distribution digest of one served cross-section (finite entries
    only; an all-NaN day digests honestly to n=0 + null moments)."""
    vals = np.asarray(scores, np.float64).reshape(-1)
    vals = vals[np.isfinite(vals)]
    if vals.size == 0:
        return {"n": 0, "mean": None, "std": None, "min": None,
                "max": None,
                **{f"p{int(q * 100)}": None for q in _QUANTILES}}
    qs = np.quantile(vals, _QUANTILES)
    return {
        "n": int(vals.size),
        "mean": round(float(vals.mean()), 6),
        "std": round(float(vals.std()), 6),
        "min": round(float(vals.min()), 6),
        "max": round(float(vals.max()), 6),
        **{f"p{int(q * 100)}": round(float(v), 6)
           for q, v in zip(_QUANTILES, qs)},
    }


def rank_correlation(a: np.ndarray, b: np.ndarray) -> Optional[float]:
    """Spearman rank correlation of two paired score vectors (average
    ranks for ties — the same convention ops.stats.masked_spearman
    uses), or None when fewer than 3 finite pairs exist."""
    a = np.asarray(a, np.float64).reshape(-1)
    b = np.asarray(b, np.float64).reshape(-1)
    ok = np.isfinite(a) & np.isfinite(b)
    if ok.sum() < 3:
        return None
    a, b = a[ok], b[ok]

    def avg_rank(x: np.ndarray) -> np.ndarray:
        order = np.argsort(x, kind="stable")
        ranks = np.empty(x.size, np.float64)
        ranks[order] = np.arange(x.size, dtype=np.float64)
        # tie groups share their mean rank
        sx = x[order]
        i = 0
        while i < sx.size:
            j = i
            while j + 1 < sx.size and sx[j + 1] == sx[i]:
                j += 1
            if j > i:
                ranks[order[i:j + 1]] = (i + j) / 2.0
            i = j + 1
        return ranks

    ra, rb = avg_rank(a), avg_rank(b)
    sa, sb = ra.std(), rb.std()
    if sa == 0.0 or sb == 0.0:
        return None  # a constant ranking correlates with nothing
    c = float(np.mean((ra - ra.mean()) * (rb - rb.mean())) / (sa * sb))
    return round(c, 6)


class ScoreDriftMonitor:
    """Per-model drift state over the daemon's served scores.

    `observe(model, day, names, scores)` is idempotent per
    (model, day): the first sighting computes the digest, pairs the
    cross-section with the model's previously-served day by instrument
    name, and (when enough names overlap) scores the day-over-day rank
    correlation — emitting the timeline marks and flipping `drifting`
    when it lands below `threshold`. Repeat sightings return the cached
    digest and emit nothing, so the request path pays once per scored
    day, not once per request."""

    def __init__(self, threshold: float = 0.5, min_overlap: int = 8):
        self.threshold = float(threshold)
        self.min_overlap = max(3, int(min_overlap))
        # model -> {"days": {day: digest}, "last_day", "last_scores"
        #           (name -> score), "last_corr", "drift_events"}
        self._models: Dict[str, dict] = {}
        # Per-model threshold overrides (walk-forward promotion policy,
        # ISSUE 14): `threshold` above is the daemon-wide default
        # (--drift_threshold); a model admitted with its own gate —
        # POST /admit's drift_threshold, or set_threshold — judges its
        # day-over-day correlation against that instead. The active
        # value is exposed per model on /stats and /metrics.
        self._thresholds: Dict[str, float] = {}
        # Guards the per-model state (graftlint JGL009): observe()
        # runs on whatever thread answers scoring requests while
        # `GET /metrics` reads stats() — the LatencyHistogram pattern.
        self._lock = threading.Lock()

    def set_threshold(self, model: str,
                      threshold: Optional[float]) -> None:
        """Per-model drift threshold (None clears the override back to
        the monitor-wide default)."""
        with self._lock:
            if threshold is None:
                self._thresholds.pop(str(model), None)
            else:
                self._thresholds[str(model)] = float(threshold)

    def threshold_for(self, model: str) -> float:
        """The ACTIVE threshold for one model (override or default)."""
        with self._lock:
            return self._thresholds.get(str(model), self.threshold)

    def observe(self, model: str, day: int,
                names: Sequence[str], scores: np.ndarray,
                alias: Optional[str] = None) -> Optional[dict]:
        """Digest one served (model, day) cross-section; returns the
        digest (cached on repeats, None for empty cross-sections)."""
        with self._lock:
            return self._observe(model, day, names, scores, alias)

    def _observe(self, model: str, day: int,
                 names: Sequence[str], scores: np.ndarray,
                 alias: Optional[str]) -> Optional[dict]:
        st = self._models.setdefault(
            model, {"days": {}, "last_day": None, "last_scores": None,
                    "last_corr": None, "drift_events": 0})
        day = int(day)
        if day in st["days"]:
            return st["days"][day]
        vals = np.asarray(scores, np.float64).reshape(-1)
        digest = score_digest(vals)
        st["days"][day] = digest
        timeline_event(DIGEST_MARK, cat="serve", resource="serve",
                       model=model, alias=alias, day=day, **digest)
        by_name = {str(n): float(v) for n, v in zip(names, vals)}
        prev_day, prev = st["last_day"], st["last_scores"]
        # only a DIFFERENT day advances the day-over-day chain; it need
        # not be adjacent — the daemon sees whatever days clients ask
        # for, and the drift signal is "vs the last served day"
        if prev is not None and prev_day != day:
            common = sorted(set(by_name) & set(prev))
            if len(common) >= self.min_overlap:
                corr = rank_correlation(
                    np.array([by_name[n] for n in common]),
                    np.array([prev[n] for n in common]))
                if corr is not None:
                    st["last_corr"] = corr
                    threshold = self._thresholds.get(model,
                                                     self.threshold)
                    if corr < threshold:
                        st["drift_events"] += 1
                        timeline_event(
                            DRIFT_MARK, cat="serve", resource="serve",
                            model=model, alias=alias, day=day,
                            prev_day=prev_day, rank_corr=corr,
                            threshold=threshold,
                            n_common=len(common))
        # days can arrive out of order (backtest replays): the chain
        # follows ARRIVAL order — yesterday is "the day this model
        # served before this one", the serving-side contract
        st["last_day"], st["last_scores"] = day, by_name
        return digest

    # ---- read side -------------------------------------------------------

    def models(self) -> List[str]:
        with self._lock:
            return sorted(self._models)

    def drifting(self, model: str) -> bool:
        """Current drift state: the model's latest day-over-day rank
        correlation landed below its ACTIVE threshold (False until a
        correlation exists). The walk-forward judge stage promotes this
        from alert to refit trigger (factorvae_tpu/wf)."""
        with self._lock:
            st = self._models.get(str(model))
            if st is None or st["last_corr"] is None:
                return False
            threshold = self._thresholds.get(str(model), self.threshold)
            return st["last_corr"] < threshold

    def stats(self) -> dict:
        """Per-model drift summary for /stats and /metrics: digests,
        last correlation, drift-event count, the ACTIVE threshold and
        the current drift state."""
        out = {}
        with self._lock:
            for model, st in sorted(self._models.items()):
                threshold = self._thresholds.get(model, self.threshold)
                out[model] = {
                    "days_digested": len(st["days"]),
                    "last_day": st["last_day"],
                    "last_rank_corr": st["last_corr"],
                    "drift_events": st["drift_events"],
                    "threshold": threshold,
                    "drifting": bool(st["last_corr"] is not None
                                     and st["last_corr"] < threshold),
                }
        return out
