# graftlint: hot-path (these run inside the jitted epoch scans)
"""On-device training-health probes.

Every probe is a SCALAR accumulated per step inside the existing epoch
scan (train/loop.py), so the whole catalog rides the aux pytree that the
scan already carries: zero extra dispatches, one host fetch per epoch
(the same fetch the loss metrics already pay), and — because every
finalized value is a scalar — the fleet's vmapped entry points return
(S,)-shaped probe dicts with no code changes (the `train/loop.py` fleet
contract).

Per-step aux (raw, un-reduced; produced by `loss_probes`/`grad_probes`):

    nf_loss        non-finite per-day losses among REAL days this step
    mu_spread_sum  day-weighted sum of std_K(posterior factor mu)
    sigma_mean_sum day-weighted sum of mean_K(posterior factor sigma)
    grad_norm      optax.global_norm of the step's gradients
    update_norm    optax.global_norm of the optimizer update
    param_norm     optax.global_norm of the post-update params
    nonfinite_grads  count of non-finite gradient ELEMENTS this step

Finalized per-epoch metrics (`finalize_*_probes`; `TRAIN_PROBE_KEYS` /
`EVAL_PROBE_KEYS` name them for the trainers and obs.report):

    grad_norm_max / grad_norm_mean / update_norm_mean / param_norm_last
    nonfinite_grads / nonfinite_loss (epoch totals)
    factor_mu_spread / factor_sigma_mean (day-weighted epoch means)

The probes observe values the update path already computes; they feed
nothing back into it, so enabling them must not change training — the
bitwise-off AND params-equal-on pins live in tests/test_obs.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax

# Epoch-level probe metric names, in reporting order. The trainers use
# these to lift probe values into the epoch record; obs.report uses them
# to know which health checks have data.
TRAIN_PROBE_KEYS = (
    "grad_norm_max",
    "grad_norm_mean",
    "update_norm_mean",
    "param_norm_last",
    "nonfinite_grads",
    "nonfinite_loss",
    "factor_mu_spread",
    "factor_sigma_mean",
)
EVAL_PROBE_KEYS = (
    "nonfinite_loss",
    "factor_mu_spread",
    "factor_sigma_mean",
)
# Mixed-precision probes (ISSUE 16): compiled into finalize_train by
# every MIXED build (train/loop.py), not gated on obs_probes — the
# dynamic loss scale is training state the host must see to flag a
# collapse (obs/report.py `loss_scale_collapse`), the way
# `skipped_steps` already rides every guarded build.
MIXED_PROBE_KEYS = (
    "loss_scale",
    "loss_scale_floor_steps",
)


def _count_nonfinite(tree) -> jnp.ndarray:
    """Total non-finite elements across a pytree, as a float32 scalar."""
    counts = jax.tree.map(
        lambda g: jnp.sum(~jnp.isfinite(g)).astype(jnp.float32), tree)
    return jax.tree.reduce(jnp.add, counts, jnp.zeros((), jnp.float32))


def loss_probes(out, day_w: jnp.ndarray) -> dict:
    """Forward-pass probes from one step's day-batched model output.

    `out` is a FactorVAEOutput with (B,)-shaped per-day losses and
    (B, K) posterior moments; `day_w` is the (B,) real-day weight (0 on
    epoch padding). Padded days gather day 0's data, so their values are
    finite garbage — every probe is day-weighted to exclude them.
    """
    f32 = jnp.float32
    return {
        "nf_loss": jnp.sum((~jnp.isfinite(out.loss)).astype(f32) * day_w),
        "mu_spread_sum": jnp.sum(
            jnp.std(out.factor_mu.astype(f32), axis=-1) * day_w),
        "sigma_mean_sum": jnp.sum(
            jnp.mean(out.factor_sigma.astype(f32), axis=-1) * day_w),
    }


def grad_probes(grads, updates, new_params) -> dict:
    """Backward-pass probes from one optimizer step."""
    return {
        "grad_norm": optax.global_norm(grads),
        "update_norm": optax.global_norm(updates),
        "param_norm": optax.global_norm(new_params),
        "nonfinite_grads": _count_nonfinite(grads),
    }


def finalize_train_probes(auxes, days: jnp.ndarray) -> dict:
    """(steps,) probe aux -> scalar epoch metrics. `days` is the epoch's
    real-day count (already clamped >= 1 by the caller's loss
    finalizer)."""
    return {
        "grad_norm_max": jnp.max(auxes["grad_norm"]),
        "grad_norm_mean": jnp.mean(auxes["grad_norm"]),
        "update_norm_mean": jnp.mean(auxes["update_norm"]),
        # the post-update norm after the LAST step — the epoch's
        # parameter-scale snapshot
        "param_norm_last": auxes["param_norm"][-1],
        "nonfinite_grads": jnp.sum(auxes["nonfinite_grads"]),
        "nonfinite_loss": jnp.sum(auxes["nf_loss"]),
        "factor_mu_spread": jnp.sum(auxes["mu_spread_sum"]) / days,
        "factor_sigma_mean": jnp.sum(auxes["sigma_mean_sum"]) / days,
    }


def loss_scale_probes(auxes, floor) -> dict:
    """(steps,) loss-scale aux -> the epoch's mixed-precision metrics:
    the scale AFTER the last step (the value the next epoch resumes at)
    and how many steps sat at the floor — the `loss_scale_collapse`
    signal (a healthy run backs off a few times then stabilizes well
    above the floor; pinned there, every step is overflowing and bf16
    training is no longer learning). Scalars, so the fleet vmap returns
    them per-lane like every other metric."""
    return {
        "loss_scale": auxes["loss_scale"][-1],
        "loss_scale_floor_steps": jnp.sum(
            (auxes["loss_scale"] <= floor).astype(jnp.float32)),
    }


def finalize_eval_probes(auxes, days: jnp.ndarray) -> dict:
    return {
        "nonfinite_loss": jnp.sum(auxes["nf_loss"]),
        "factor_mu_spread": jnp.sum(auxes["mu_spread_sum"]) / days,
        "factor_sigma_mean": jnp.sum(auxes["sigma_mean_sum"]) / days,
    }
