"""Run report: per-epoch health tables + flags from a RUN.jsonl.

    python -m factorvae_tpu.obs.report RUN.jsonl [--json] [--follow]
        [--spike-mult 10] [--slow-frac 0.5] [--diverge-frac 0.2]
        [--diverge-epochs 3]

`--follow` tails an IN-FLIGHT stream instead (delegating to
`obs/live.py`, pillar 5): the same flags, emitted as alerts while the
run is still writing, pinned identical to this report run post-hoc.

Aggregates the metric stream (epoch / fleet_epoch records, the health
probes when `obs` was on, the `plan` decision block, the compiled-
program `compile` records, scores/best events) into one table and
raises health flags:

- `nonfinite`     — NaN/inf train or val loss, non-finite gradient
                    elements, or non-finite per-day losses (the probe
                    counters). This is the flag that would have caught
                    the PR-4 donation bug (NaN epoch-3 losses after
                    resume) in the first epoch record instead of a
                    root-cause hunt.
- `grad_spike`    — grad_norm_max > spike-mult x the run's median
                    grad_norm_mean (needs `obs` probes).
- `val_divergence`— val loss sitting >= diverge-frac above its best for
                    diverge-epochs consecutive epochs while training
                    continues (classic overfit/collapse signature).
- `slow_epoch`    — days_per_sec below slow-frac x the run median, and
                    (when the planner's measured envelope is in the
                    stream) below slow-frac x the plan row's measured
                    rate — a throughput regression against the envelope
                    the planner promised.
- `loss_scale_collapse`
                  — the mixed-precision dynamic loss scale spent steps
                    pinned at its floor this epoch
                    (`loss_scale_floor_steps` probe; per seed lane on
                    fleets). A bf16 lane overflowing faster than the
                    backoff can absorb is silently skipping its updates
                    wholesale — the lane has numerically collapsed even
                    though every loss it reports is finite (ISSUE 16).
- `compile_storm` — a retrace storm, now with its COST dimension: the
                    per-miss `compile` records say what the storm burned
                    in compile wall seconds (ISSUE 7).
- `hbm_over_budget` / `compile_over_budget`
                  — a `compile` record whose program peak-HBM estimate
                    or compile wall exceeds the governing plan row's
                    optional `budgets` envelope (plan.py
                    budget_peak_hbm_bytes / budget_compile_s; rows
                    without the block promise nothing and flag nothing).

Recovery events (ISSUE 9, docs/robustness.md) render as first-class
flags too — a run that HEALED is not a clean run, and the report is
where the healing becomes visible:

- `skip_step`      — the in-graph finite guard skipped updates this
                     epoch (`skipped_steps` metric; per seed lane on
                     fleets).
- `rollback`       — host-side escalation restored a checkpoint
                     (`recovery` events: serial rollback + lr backoff,
                     or a fleet lane rolling back alone; the
                     *_unavailable kinds mean it wanted to and could
                     not).
- `quarantine`     — a checkpoint step or serve weights directory
                     failed sha256 manifest verification and was fenced
                     (`ckpt_quarantine` / `serve_quarantine` marks).
- `circuit_open`   — a served model's breaker opened after K
                     consecutive failures (`circuit_open` marks).
- `retry`          — a bounded-backoff retry fired (`stream_retry` /
                     `cold_start_retry` marks): the fault healed below
                     the epoch/request level.

Served-score drift (ISSUE 10) renders as `score_drift`: the scoring
daemon's drift monitor (obs/drift.py) saw a model's day-over-day
served rank correlation collapse below its threshold — the signal
degraded while every request kept answering 200.

Human output by default; `--json` for the machine-readable form. An
empty, missing, or non-JSONL stream exits with a one-line error; a
trailing torn line (async-kill artifact) is a warning, never fatal.
"""

from __future__ import annotations

import json
import math
import re
from statistics import median
from typing import List, Optional

from factorvae_tpu.obs.probes import TRAIN_PROBE_KEYS
from factorvae_tpu.obs.timeline import (
    RunStreamError,
    compile_summary,
    load_run,
    open_run,
)

# load_run/open_run are re-exported CLI plumbing here; keeping the names
# referenced preserves the public import path tests rely on.
__all__ = ["build_report", "drift_flags", "format_report",
           "health_flags", "load_run", "main", "open_run",
           "plan_measured_days_per_sec", "program_flags",
           "recovery_flags"]

# timeline marks that announce a recovery action -> report flag name
RECOVERY_MARK_FLAGS = {
    "ckpt_quarantine": "quarantine",
    "serve_quarantine": "quarantine",
    "circuit_open": "circuit_open",
    "stream_retry": "retry",
    "cold_start_retry": "retry",
}

# serve-side drift marks (obs/drift.py) -> report flag name. Distinct
# from recovery: the daemon took no action — the SIGNAL degraded, and
# the report is where that becomes a first-class flag (ISSUE 10).
DRIFT_MARK_FLAGS = {
    "score_drift": "score_drift",
}

# autotune_plan rows carry "train 0.1234 s/day" in their source string;
# a matched value is the measured envelope the planner promised.
_PLAN_RATE_RE = re.compile(r"train ([0-9.eE+-]+) s/day")


def _nums(v) -> List[float]:
    """Numeric leaves of an epoch-record value (fleet records hold
    per-seed lists; serial records hold scalars)."""
    if isinstance(v, (int, float)):
        return [float(v)]
    if isinstance(v, list):
        return [float(x) for x in v if isinstance(x, (int, float))]
    return []


def _any_nonfinite(v) -> bool:
    return any(not math.isfinite(x) for x in _nums(v))


def _mean(v) -> Optional[float]:
    xs = [x for x in _nums(v) if math.isfinite(x)]
    return sum(xs) / len(xs) if xs else None


def _parse_plan_rate(rec: dict) -> Optional[float]:
    """Measured train rate promised by ONE `plan` record, or None —
    default-provenance plans promise no envelope."""
    if rec.get("provenance") != "measured":
        return None
    m = _PLAN_RATE_RE.search(str(rec.get("source", "")))
    if not m:
        return None
    try:
        s_per_day = float(m.group(1))
        return 1.0 / s_per_day if s_per_day > 0 else None
    except ValueError:
        return None


def plan_measured_days_per_sec(events: List[dict]) -> Optional[float]:
    """Envelope of the stream's FIRST plan record (single-run streams)."""
    for rec in events:
        if rec.get("event") == "plan":
            return _parse_plan_rate(rec)
    return None


def _plan_rate_for(seg: List[dict], events: List[dict]) -> Optional[float]:
    """The plan envelope governing THIS segment: the last `plan` record
    the stream logged before the segment's first epoch (record order via
    the `_line` annotation obs.timeline.load_run attaches). A plan from
    a different run in a concatenated session must not set the envelope
    here — and a run whose own plan was default-provenance gets none.
    Hand-built record lists without `_line` fall back to the stream's
    first plan record."""
    plans = [r for r in events if r.get("event") == "plan"]
    if not plans:
        return None
    first = seg[0].get("_line") if seg else None
    if first is not None and all(p.get("_line") is not None for p in plans):
        prior = [p for p in plans if p["_line"] < first]
        if not prior:
            return None
        return _parse_plan_rate(prior[-1])
    return _parse_plan_rate(plans[0])


def _segments(epochs: List[dict]) -> List[List[dict]]:
    """Split a (possibly concatenated) stream's epoch records into
    per-run segments. One RUN.jsonl deliberately carries many runs —
    autotune + train + sweep sessions, parity grid points, fleet groups
    — and the stateful health checks (divergence baselines, throughput
    medians, the compile-epoch exemption) must not leak across run
    boundaries. A new segment starts wherever the epoch number fails to
    increase: a fresh run restarts at 0 (or any earlier epoch), while a
    resume continues its predecessor's numbering and correctly extends
    the segment."""
    segs: List[List[dict]] = []
    cur: List[dict] = []
    last: Optional[float] = None
    for rec in epochs:
        e = rec.get("epoch")
        if cur and isinstance(e, (int, float)) \
                and isinstance(last, (int, float)) and e <= last:
            segs.append(cur)
            cur = []
        cur.append(rec)
        if isinstance(e, (int, float)):
            last = e
    if cur:
        segs.append(cur)
    return segs


def _lane_count(seg: List[dict], key: str) -> int:
    """Seed-lane width of a metric over a segment: fleets log per-seed
    LISTS, serial runs scalars (width 1). Health checks run per lane so
    one bad seed is never diluted by the healthy majority ("flags fire
    if ANY seed trips")."""
    return max((len(_nums(r.get(key))) for r in seg), default=0)


def _lane(rec: dict, key: str, s: int) -> Optional[float]:
    lanes = _nums(rec.get(key))
    return lanes[s] if s < len(lanes) else None


def _lane_name(recs, s: int) -> Optional[str]:
    """The lane-CONFIG label for seed lane `s`, from the newest record
    carrying `lane_labels` (fleet epoch records since ISSUE 12: the
    hyper fleet races DIFFERENT configs per lane, so an alert must name
    the config that diverged — lr/kl_weight/config hash — not just the
    lane index). None on pre-ISSUE-12 streams."""
    if isinstance(recs, dict):
        recs = [recs]
    for rec in reversed(list(recs)):
        labels = rec.get("lane_labels")
        if isinstance(labels, list) and s < len(labels) \
                and isinstance(labels[s], str):
            return labels[s]
    return None


def _seed_tag(recs, s: int, width: int) -> str:
    """' (seed lane N)' / ' (seed lane N: <config label>)' / '' — ONE
    formatter for every per-lane flag detail, so obs.report, obs.live
    and the skip_step recovery flags name lanes identically."""
    if width <= 1:
        return ""
    name = _lane_name(recs, s)
    return (f" (seed lane {s}: {name})" if name
            else f" (seed lane {s})")


def health_flags(epochs: List[dict], events: List[dict],
                 spike_mult: float = 10.0, slow_frac: float = 0.5,
                 diverge_frac: float = 0.2,
                 diverge_epochs: int = 3) -> List[dict]:
    flags: List[dict] = []

    def flag(rec, kind, detail):
        # `line` (the load_run stream position) identifies the exact
        # record: in a concatenated multi-run stream, epoch NUMBERS
        # repeat across runs and must not be the join key.
        flags.append({"epoch": rec.get("epoch"), "line": rec.get("_line"),
                      "flag": kind, "detail": detail})

    def seed_tag(rec, s: int, width: int) -> str:
        return _seed_tag(rec, s, width)

    # Every stateful check runs PER SEGMENT (per run): baselines,
    # medians, exemptions and the plan envelope from one grid point or
    # fleet group must not flag — or excuse — the next one.
    for seg in _segments(epochs):
        # nonfinite: losses + probe counters. A run with NO validation
        # split records NaN val_loss every epoch BY DESIGN — the
        # exemption is judged over THIS run only, so a sibling run's
        # finite val split can't un-excuse it.
        no_val = all(_any_nonfinite(r.get("val_loss", 0.0)) for r in seg)
        for rec in seg:
            for key in ("train_loss", "val_loss"):
                if key in rec and _any_nonfinite(rec[key]):
                    if key == "val_loss" and no_val:
                        continue
                    flag(rec, "nonfinite",
                         f"{key} is not finite: {rec[key]}")
            for key in ("nonfinite_grads", "nonfinite_loss",
                        "val_nonfinite_loss"):
                n = _mean(rec.get(key, 0.0))
                if n and n > 0:
                    flag(rec, "nonfinite", f"{key}={n:g} (probe counter)")

        # loss-scale collapse (mixed precision, ISSUE 16): the dynamic
        # loss scale spent steps pinned at its configured floor this
        # epoch. Every one of those steps overflowed AND could not back
        # off further — the lane is shedding updates wholesale while
        # its reported losses stay finite, so nothing else flags it.
        s_ls = _lane_count(seg, "loss_scale_floor_steps")
        for rec in seg:
            for s in range(s_ls):
                n = _lane(rec, "loss_scale_floor_steps", s)
                if n is None or n <= 0:
                    continue
                scale = _lane(rec, "loss_scale", s)
                at = (f", scale={scale:g}" if scale is not None
                      and math.isfinite(scale) else "")
                flag(rec, "loss_scale_collapse",
                     f"loss scale pinned at its floor for {n:g} "
                     f"overflowed step(s){at}"
                     + seed_tag(rec, s, s_ls))

        # grad spikes (probe data required), per seed lane: each seed
        # is judged against ITS OWN epoch-median grad_norm_mean
        s_grad = _lane_count(seg, "grad_norm_mean")
        for s in range(s_grad):
            means = [m for r in seg
                     for m in [_lane(r, "grad_norm_mean", s)]
                     if m is not None and math.isfinite(m)]
            if not means:
                continue
            base = median(means)
            for rec in seg:
                gmax = _lane(rec, "grad_norm_max", s)
                if gmax is not None and base > 0 \
                        and gmax > spike_mult * base:
                    flag(rec, "grad_spike",
                         f"grad_norm_max={gmax:.4g} > {spike_mult:g}x "
                         f"median grad_norm_mean ({base:.4g})"
                         + seed_tag(rec, s, s_grad))

        # val divergence, per seed lane: >= diverge_epochs consecutive
        # epochs sitting diverge_frac above that seed's best in this run
        s_val = _lane_count(seg, "val_loss")
        for s in range(s_val):
            best = math.inf
            streak: List[dict] = []
            for rec in seg:
                v = _lane(rec, "val_loss", s)
                if v is None or not math.isfinite(v):
                    continue
                if math.isfinite(best) and v > best * (1.0 + diverge_frac):
                    streak.append(rec)
                    if len(streak) == diverge_epochs:
                        flag(streak[0], "val_divergence",
                             f"val loss >= {1 + diverge_frac:g}x its "
                             f"best ({best:.6g}) for {diverge_epochs} "
                             "consecutive epochs (through epoch "
                             f"{rec.get('epoch')})"
                             + seed_tag(streak[0], s, s_val))
                else:
                    streak = []
                best = min(best, v)

        # throughput: vs this run's median, and vs THIS run's plan
        # envelope (the last plan record logged before this segment).
        # Each run's FIRST epoch record pays jit compilation and is
        # exempt — flagging every cold start would train readers to
        # ignore the flag.
        plan_rate = _plan_rate_for(seg, events)
        timed = seg[1:] if len(seg) > 1 else seg
        rates = [r for rec in timed
                 for r in [_mean(rec.get("days_per_sec",
                                         rec.get("seed_days_per_sec")))]
                 if r is not None and r > 0]
        if rates:
            run_median = median(rates)
            for rec in timed:
                r = _mean(rec.get("days_per_sec",
                                  rec.get("seed_days_per_sec")))
                if r is None or r <= 0:
                    continue
                if r < slow_frac * run_median:
                    flag(rec, "slow_epoch",
                         f"{r:.3g} days/s < {slow_frac:g}x run median "
                         f"({run_median:.3g})")
                elif plan_rate is not None and r < slow_frac * plan_rate:
                    flag(rec, "slow_epoch",
                         f"{r:.3g} days/s < {slow_frac:g}x the plan "
                         f"row's measured {plan_rate:.3g} days/s")
    return flags


def _budgets_for(rec: dict, events: List[dict]) -> dict:
    """The observability budgets governing one `compile` record: the
    last `plan` record the stream logged before it (same record-order
    rule as `_plan_rate_for`). {} when no plan with budgets precedes it
    — budgets are opt-in, and a plan from a LATER run must not judge an
    earlier program."""
    plans = [r for r in events if r.get("event") == "plan"]
    line = rec.get("_line")
    if line is not None and all(p.get("_line") is not None for p in plans):
        plans = [p for p in plans if p["_line"] < line]
    if not plans:
        return {}
    p = plans[-1]
    return {
        "compile_s": float(p.get("budget_compile_s") or 0.0),
        "peak_hbm_bytes": int(p.get("budget_peak_hbm_bytes") or 0),
    }


def program_flags(run: dict) -> List[dict]:
    """Compiled-program flags (ISSUE 7), judged per RECORD rather than
    per epoch: retrace storms with their measured compile-wall cost,
    and compile records past the governing plan row's budgets."""
    flags: List[dict] = []
    events = run.get("events", [])
    compiles = [r for r in events if r.get("event") == "compile"]

    # compile_storm: one flag per stormed jit, worst mark wins; the
    # cost dimension comes from that jit's compile records.
    storms: dict = {}
    for m in run.get("marks", []):
        if m.get("name") != "retrace_storm":
            continue
        fn = m.get("fn")
        prev = storms.get(fn)
        if prev is None or (m.get("compiles") or 0) > (prev.get("compiles")
                                                       or 0):
            storms[fn] = m
    for fn, m in storms.items():
        cost = sum(float(c.get("wall_s") or 0.0)
                   for c in compiles if c.get("fn") == fn)
        flags.append({
            "epoch": None, "line": m.get("_line"), "flag": "compile_storm",
            "detail": f"'{fn}' compiled {m.get('compiles')}x over "
                      f"{m.get('calls')} calls"
                      + (f" — {cost:.2f}s of compile wall burned"
                         if cost else ""),
        })

    for c in compiles:
        budgets = _budgets_for(c, events)
        peak_budget = budgets.get("peak_hbm_bytes") or 0
        peak = c.get("peak_bytes")
        if peak_budget > 0 and peak is not None and peak > peak_budget:
            flags.append({
                "epoch": None, "line": c.get("_line"),
                "flag": "hbm_over_budget",
                "detail": f"'{c.get('fn')}' program peak HBM estimate "
                          f"{peak / 1e6:.1f} MB > budget "
                          f"{peak_budget / 1e6:.1f} MB (plan row)",
            })
        s_budget = budgets.get("compile_s") or 0.0
        wall = c.get("wall_s")
        if s_budget > 0 and wall is not None and wall > s_budget:
            flags.append({
                "epoch": None, "line": c.get("_line"),
                "flag": "compile_over_budget",
                "detail": f"'{c.get('fn')}' compile wall {wall:.2f}s > "
                          f"budget {s_budget:g}s (plan row)",
            })
    return flags


def recovery_flags(run: dict) -> List[dict]:
    """Recovery actions (ISSUE 9) as first-class flags. Three sources:
    epoch records whose `skipped_steps` metric shows the in-graph
    finite guard fired (per seed lane on fleets), `recovery` logger
    events (rollbacks — including the *_unavailable kinds, which mean
    the escalation wanted a checkpoint and had none), and the recovery
    timeline marks (quarantines, circuit breakers, bounded retries)."""
    flags: List[dict] = []
    for rec in run.get("epochs", []):
        if "skipped_steps" not in rec:
            continue
        lanes = _nums(rec.get("skipped_steps"))
        hit = [(s, n) for s, n in enumerate(lanes) if n > 0]
        if not hit:
            continue
        width = len(lanes)
        detail = ", ".join(
            f"{n:g} update(s) skipped" + _seed_tag(rec, s, width)
            for s, n in hit)
        flags.append({"epoch": rec.get("epoch"), "line": rec.get("_line"),
                      "flag": "skip_step",
                      "detail": f"finite guard: {detail}"})
    for rec in run.get("events", []):
        if rec.get("event") != "recovery":
            continue
        kind = rec.get("kind", "rollback")
        if kind in ("rollback", "lane_rollback"):
            lane = (f"seed lane {rec['lane']} " if "lane" in rec else "")
            lr = (f", lr_scale={rec['lr_scale']:g}"
                  if isinstance(rec.get("lr_scale"), (int, float)) else "")
            detail = (f"{lane}rolled back to checkpoint step "
                      f"{rec.get('restored_step')}{lr}")
        else:
            detail = f"{kind}: {rec.get('note', '')}".strip(": ")
        flags.append({"epoch": rec.get("epoch"), "line": rec.get("_line"),
                      "flag": "rollback", "detail": detail})
    for m in run.get("marks", []):
        kind = RECOVERY_MARK_FLAGS.get(m.get("name"))
        if kind is None:
            continue
        what = {k: v for k, v in m.items()
                if k in ("step", "reason", "model", "path", "chunk",
                         "attempt", "error", "fails")}
        detail = (m.get("name") + (" " + " ".join(
            f"{k}={v}" for k, v in sorted(what.items())) if what else ""))
        flags.append({"epoch": m.get("epoch"), "line": m.get("_line"),
                      "flag": kind, "detail": detail})
    flags.sort(key=lambda f: (f.get("line") is None, f.get("line") or 0))
    return flags


def drift_flags(run: dict) -> List[dict]:
    """Served-score drift (ISSUE 10; obs/drift.py emits the marks): a
    model whose day-over-day served ranking collapsed below the drift
    threshold — the Rank-IC-decay signature of regime shift — raises a
    `score_drift` flag per mark."""
    flags: List[dict] = []
    for m in run.get("marks", []):
        kind = DRIFT_MARK_FLAGS.get(m.get("name"))
        if kind is None:
            continue
        corr = m.get("rank_corr")
        corr_s = (f"{corr:.3f}" if isinstance(corr, (int, float))
                  else str(corr))
        flags.append({
            "epoch": None, "line": m.get("_line"), "flag": kind,
            "detail": (f"model {m.get('alias') or m.get('model')}: "
                       f"day-over-day rank corr {corr_s} < "
                       f"{m.get('threshold')} (day {m.get('day')} vs "
                       f"{m.get('prev_day')}, n={m.get('n_common')})"),
        })
    return flags


def build_report(run: dict, **kw) -> dict:
    epochs = run["epochs"]
    flags = health_flags(epochs, run["events"], **kw)
    flags += program_flags(run)
    flags += drift_flags(run)
    recov = recovery_flags(run)
    flags += recov
    by_kind: dict = {}
    for f in flags:
        by_kind[f["flag"]] = by_kind.get(f["flag"], 0) + 1
    finals = [r for r in run["events"] if r.get("event") in ("best",
                                                            "fleet_best")]
    scores = [r for r in run["events"] if r.get("event") == "scores"]
    probes_on = any(k in rec for rec in epochs for k in TRAIN_PROBE_KEYS)
    return {
        "meta": run["meta"][-1] if run["meta"] else None,
        "num_epochs": len(epochs),
        "probes": probes_on,
        "epochs": epochs,
        "compiles": compile_summary(run),
        "flags": flags,
        "summary": {
            "flag_counts": by_kind,
            "healthy": not flags,
            # recovery actions alone (subset of flag_counts): the run
            # took damage AND healed — distinct from undetected-problem
            # flags like grad_spike
            "recovery_counts": {
                k: n for k, n in sorted(by_kind.items())
                if k in ("skip_step", "rollback", "quarantine",
                         "circuit_open", "retry")},
            "best": finals[-1] if finals else None,
            "scores": scores[-1] if scores else None,
        },
    }


def _flag_matches(f: dict, rec: dict) -> bool:
    """Row join for the table: by stream position when both sides have
    it (epoch numbers repeat across concatenated runs), else by epoch
    number (hand-built record lists)."""
    if f.get("line") is not None and rec.get("_line") is not None:
        return f["line"] == rec["_line"]
    return f["epoch"] == rec.get("epoch")


def format_report(rep: dict) -> str:
    lines = []
    meta = rep["meta"] or {}
    lines.append(
        f"run: {meta.get('run_name') or '?'}  platform="
        f"{meta.get('platform')}  devices={meta.get('device_count')}  "
        f"git={meta.get('git_sha')}  config={meta.get('config_hash')}")
    lines.append(f"epochs: {rep['num_epochs']}   health probes: "
                 f"{'on' if rep['probes'] else 'off'}")
    comp = rep.get("compiles") or {}
    if comp.get("records"):
        peak = comp.get("max_peak_bytes")
        lines.append(
            f"compiled programs: {len(comp['by_fn'])} jits / "
            f"{comp['records']} compiles, "
            f"{comp['total_wall_s']:.2f}s compile wall"
            + (f", peak program HBM estimate {peak / 1e6:.1f} MB"
               if peak else ""))
    if rep["epochs"]:
        cols = ["epoch", "train_loss", "val_loss", "lr", "days_per_sec"]
        if rep["probes"]:
            cols += ["grad_norm_max", "nonfinite_grads"]
        lines.append("  ".join(f"{c:>13}" for c in cols) + "  flags")
        for rec in rep["epochs"]:
            row = []
            for c in cols:
                v = _mean(rec.get(c)) if c != "epoch" else rec.get(c)
                row.append(f"{v:>13.6g}" if isinstance(v, (int, float))
                           else f"{'-':>13}")
            marks = sorted({f["flag"] for f in rep["flags"]
                            if _flag_matches(f, rec)})
            lines.append("  ".join(row) + ("  !! " + ",".join(marks)
                                           if marks else ""))
        if any(isinstance(r.get("train_loss"), list) for r in rep["epochs"]):
            lines.append("(fleet run: per-seed lists reported as means; "
                         "flags fire if ANY seed trips)")
    if rep["flags"]:
        lines.append("")
        lines.append(f"HEALTH FLAGS ({len(rep['flags'])}):")
        for f in rep["flags"]:
            where = (f"epoch {f['epoch']}" if f.get("epoch") is not None
                     else "program")  # compile/budget flags are per jit
            lines.append(f"  {where}: [{f['flag']}] {f['detail']}")
    else:
        lines.append("no health flags — run looks clean")
    recov = rep["summary"].get("recovery_counts") or {}
    if recov:
        lines.append(
            "recovery actions: "
            + ", ".join(f"{k} x{n}" for k, n in recov.items())
            + " (the run took damage and healed — docs/robustness.md)")
    best = rep["summary"]["best"]
    if best:
        vals = best.get("best_val")
        lines.append(f"best val: {vals}")
    sc = rep["summary"]["scores"]
    if sc:
        lines.append(f"scores: rank_ic={sc.get('rank_ic')} "
                     f"rank_ic_ir={sc.get('rank_ic_ir')} -> {sc.get('path')}")
    return "\n".join(lines)


def main(argv: Optional[list] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m factorvae_tpu.obs.report",
        description="Per-epoch health table + flags for a RUN.jsonl")
    ap.add_argument("run_jsonl")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--follow", action="store_true",
                    help="tail an in-flight stream instead of reading "
                         "a finished one: delegates to the live "
                         "follower (obs/live.py), emitting each flag "
                         "as an alert when it appears; flags are "
                         "pinned identical to this report run post-hoc")
    ap.add_argument("--idle-timeout", type=float, default=None,
                    help="with --follow: stop after this many seconds "
                         "without new bytes (default: follow forever)")
    ap.add_argument("--spike-mult", type=float, default=10.0)
    ap.add_argument("--slow-frac", type=float, default=0.5)
    ap.add_argument("--diverge-frac", type=float, default=0.2)
    ap.add_argument("--diverge-epochs", type=int, default=3)
    args = ap.parse_args(argv)
    import sys

    if args.follow:
        from factorvae_tpu.obs import live

        follow_args = [args.run_jsonl, "--follow"]
        if args.json:
            follow_args.append("--json")
        if args.idle_timeout is not None:
            follow_args += ["--idle-timeout", str(args.idle_timeout)]
        follow_args += [
            "--spike-mult", str(args.spike_mult),
            "--slow-frac", str(args.slow_frac),
            "--diverge-frac", str(args.diverge_frac),
            "--diverge-epochs", str(args.diverge_epochs)]
        return live.main(follow_args)

    try:
        run, warnings = open_run(args.run_jsonl)
    except RunStreamError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    for w in warnings:
        print(f"warning: {w}", file=sys.stderr)
    rep = build_report(
        run, spike_mult=args.spike_mult,
        slow_frac=args.slow_frac, diverge_frac=args.diverge_frac,
        diverge_epochs=args.diverge_epochs)
    if args.json:
        print(json.dumps(rep, indent=2, default=str))
    else:
        print(format_report(rep))
    return 0 if rep["num_epochs"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
