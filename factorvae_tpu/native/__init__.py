"""ctypes binding for the native panel ops, with transparent fallback.

Compiles panelops.cpp with g++ on first use (cached as _build/panelops.so
next to this file); if no compiler is available — or
``FACTORVAE_NATIVE=0`` is set — callers get ``None`` from `load()` and
use their numpy fallbacks. No pybind11 (not in the image); the ABI is
plain C (see panelops.cpp header comment).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "panelops.cpp")
_SO = os.path.join(_DIR, "_build", "panelops.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _compile() -> bool:
    os.makedirs(os.path.dirname(_SO), exist_ok=True)
    # compile to a process-unique temp path and rename atomically so a
    # concurrent first-use in another process never dlopens a half-written .so
    tmp = f"{_SO}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return True
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def load() -> Optional[ctypes.CDLL]:
    """The loaded library, or None if unavailable/disabled."""
    global _lib, _tried
    if os.environ.get("FACTORVAE_NATIVE", "1") == "0":
        return None
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
            if not _compile():
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        lib.fill_maps.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ]
        lib.scatter_panel.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.POINTER(ctypes.c_float),
        ]
        _lib = lib
        return _lib


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def fill_maps(valid: np.ndarray):
    """Native last_valid/next_valid (see windows.compute_fill_maps for the
    semantics and the numpy fallback). Returns None if native is off."""
    lib = load()
    if lib is None:
        return None
    d, i = valid.shape
    v = np.ascontiguousarray(valid, dtype=np.uint8)
    last = np.empty((d, i), np.int32)
    nxt = np.empty((d, i), np.int32)
    lib.fill_maps(
        _ptr(v, ctypes.c_uint8), d, i,
        _ptr(last, ctypes.c_int32), _ptr(nxt, ctypes.c_int32),
    )
    return last, nxt


def scatter_panel(values: np.ndarray, rows: np.ndarray, cols: np.ndarray,
                  d_total: int, n_inst: int):
    """Native COO -> dense (I, D, C) scatter with NaN background.
    Returns None if native is off."""
    lib = load()
    if lib is None:
        return None
    values = np.ascontiguousarray(values, dtype=np.float32)
    rows = np.ascontiguousarray(rows, dtype=np.int64)
    cols = np.ascontiguousarray(cols, dtype=np.int64)
    n_rows, c = values.shape
    out = np.full((n_inst, d_total, c), np.nan, np.float32)
    lib.scatter_panel(
        _ptr(values, ctypes.c_float), _ptr(rows, ctypes.c_int64),
        _ptr(cols, ctypes.c_int64), n_rows, d_total, c,
        _ptr(out, ctypes.c_float),
    )
    return out
