// Native panel ops for the host-side data pipeline.
//
// The reference's data layer is pure pandas/python (dataset.py:41-184);
// this framework's host-side preprocessing is numpy-vectorized already,
// but the two hot O(D*I) index passes — the ffill/bfill fill maps
// (windows.py) and the COO->dense panel scatter (panel.py) — are also
// provided natively for large panels (CSI800 x 20y x Alpha360). Built as
// a plain shared object, bound via ctypes (no pybind11 dependency);
// factorvae_tpu/native/__init__.py compiles it on first use and falls
// back to the numpy implementations when no compiler is available.
//
// Layout contracts (all row-major, C-contiguous):
//   valid:       (D, I) uint8
//   last_valid:  (D, I) int32   largest d' <= d with valid[d',i], else -1
//   next_valid:  (D, I) int32   smallest d' >= d with valid[d',i], else D
//   scatter: values (n_rows, C) float32 -> out (I, D, C) float32 at
//            (cols[k], rows[k], :); out must be pre-filled with NaN.

#include <cstdint>

extern "C" {

void fill_maps(const uint8_t* valid, int64_t d_total, int64_t n_inst,
               int32_t* last_valid, int32_t* next_valid) {
  for (int64_t i = 0; i < n_inst; ++i) {
    int32_t last = -1;
    for (int64_t d = 0; d < d_total; ++d) {
      if (valid[d * n_inst + i]) last = static_cast<int32_t>(d);
      last_valid[d * n_inst + i] = last;
    }
    int32_t next = static_cast<int32_t>(d_total);
    for (int64_t d = d_total - 1; d >= 0; --d) {
      if (valid[d * n_inst + i]) next = static_cast<int32_t>(d);
      next_valid[d * n_inst + i] = next;
    }
  }
}

void scatter_panel(const float* values, const int64_t* rows,
                   const int64_t* cols, int64_t n_rows, int64_t d_total,
                   int64_t n_cols_panel, float* out) {
  // out: (I, D, C); values: (n_rows, C); (rows[k]=day, cols[k]=instrument)
  for (int64_t k = 0; k < n_rows; ++k) {
    const float* src = values + k * n_cols_panel;
    float* dst = out + (cols[k] * d_total + rows[k]) * n_cols_panel;
    for (int64_t c = 0; c < n_cols_panel; ++c) dst[c] = src[c];
  }
}

}  // extern "C"
