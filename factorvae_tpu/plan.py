"""Adaptive execution planner: layout/batching/kernel decisions from
measured data instead of static defaults.

The round-5 CPU head-to-head (PERF.md) showed the fastest configuration
is a function of backend and shape: the TPU-tuned defaults
(`flatten_days=True`, `days_per_step=8`, bf16) are ~35% slower than the
reference-faithful path on CPU, and the per-shape dtype winner even
flips between the training and scoring workloads. This module owns that
decision. It generalizes the round-3 kernel auto-select
(`ops/pallas/select.py`, now a thin shim over the predicates kept here)
from "pallas on/off per raced shape" to the full execution plan:

    flatten_days · days_per_step · compute_dtype · pallas on/off ·
    cross-section pad target,

each resolved per (platform, shape) from an **envelope table** of
measured rows:

- Builtin rows encode the round-2 on-chip measurements (the flagship
  bf16/dps=8/flattened configuration behind the 35.3x row — preserved
  verbatim so the next live-relay bench reproduces it unchanged).
- `scripts/autotune_plan.py` races the candidate paths on the current
  backend (bounded, one command) and persists fresh rows to
  `PLAN_TABLE.json` (env `FACTORVAE_PLAN_TABLE`); file rows take
  precedence over builtins, so a newer measurement on the same
  (platform, shape) wins.
- Unmeasured shapes fall back to the conservative per-backend default:
  reference-faithful `days_per_step=1` un-flattened float32 on CPU, the
  round-2-measured winners (dps=8, flattened, bf16) on TPU. Provenance
  ("measured" | "default") rides on every Plan so bench.py can report
  which it got.

The same no-extrapolation rule the kernel envelope always had applies
table-wide: a row only matches inside its measured [n_min, n_max]
cross-section range.

Padding is scale-aware instead of a single global `pad_multiple`: the
pad target is computed per config from the real cross-section width, the
platform's row-tiling quantum and the stock-shard count — CSI800 pads
800 -> 800 (zero dead compute) instead of the 800 -> 1024 (28% dead
rows) the old fixed `max_stocks=1024` preset paid.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
from dataclasses import dataclass
from typing import Optional, Sequence, Union

# ---------------------------------------------------------------------------
# Pallas kernel envelope (moved verbatim from ops/pallas/select.py; that
# module now delegates here). Kernel selection is MEASURED per rig since
# ISSUE 19: `scripts/autotune_plan.py --kernels` races both kernels
# against the XLA paths at the preset shapes and persists a per-row
# "kernels" block whose verdict ("pallas" | "xla") the predicates read
# FIRST. The static envelope below — the round-2 race on a real v5e
# (RACE_KERNELS.json, N in {360, 1024}) — is only the NO-ROW fallback:
# "auto" applies those frozen winners INSIDE the raced envelope and
# resolves to XLA everywhere else (VERDICT r3 missing-#4: no
# extrapolated wins — the r3 cross-day flattening moved the production
# GRU row count to N = B*N_pad = 2880, a shape with no round-2 race
# row). Widen the *_RACED_N_MAX fallback constants only from new chip
# rows; prefer re-racing (`--kernels`) so the verdict is a measured
# block, not a code edit. See docs/kernels.md.
# ---------------------------------------------------------------------------

_GRU_RACED_N_MAX = 1024
_ATTN_RACED_N_MAX = 1024


def _on_tpu() -> bool:
    import jax

    return jax.default_backend() == "tpu"


def pallas_attention_wins(n: int, h: int, k: int,
                          on_tpu: Optional[bool] = None,
                          verdict: str = "") -> bool:
    """Whether the fused attention should run for this shape. A measured
    per-rig verdict (a plan row's "kernels" block, raced by
    `autotune_plan --kernels`) decides outright; absent one ("") the
    round-2 static envelope applies — False outside it (no extrapolated
    wins; the raced N values are {360, 1024}, both bounds measured)."""
    if verdict:
        return verdict == "pallas"
    if on_tpu is None:
        on_tpu = _on_tpu()
    return on_tpu and 360 <= n <= _ATTN_RACED_N_MAX and h <= 24


def pallas_gru_wins(n: int, t: int, h: int,
                    on_tpu: Optional[bool] = None,
                    verdict: str = "") -> bool:
    """Whether the fused GRU recurrence should run for this shape. Same
    resolution order as `pallas_attention_wins`: measured row verdict
    first, round-2 static envelope as the no-row fallback."""
    if verdict:
        return verdict == "pallas"
    if on_tpu is None:
        on_tpu = _on_tpu()
    return on_tpu and 512 <= n <= _GRU_RACED_N_MAX and h <= 24 and t <= 20


def resolve(flag, measured: bool) -> bool:
    """Resolve a config tri-state (False | True | 'auto'). Any other
    string is an error — a truthy fallback would force the kernels on
    for a typo like "off" or "Auto"."""
    if isinstance(flag, str):
        if flag == "auto":
            return measured
        raise ValueError(
            f"use_pallas_* must be False, True or 'auto'; got {flag!r}")
    return bool(flag)


# ---------------------------------------------------------------------------
# Shape key + plan
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ShapeKey:
    """The shape coordinates a plan row is keyed on. `n_stocks` is the
    REAL (pre-padding) cross-section width."""

    num_features: int   # C
    seq_len: int        # T
    hidden_size: int    # H
    num_factors: int    # K
    num_portfolios: int  # M
    n_stocks: int       # N (real)


@dataclass(frozen=True)
class Plan:
    """One resolved execution plan.

    Training knobs: `flatten_days`, `days_per_step`, `compute_dtype`.
    Scoring knobs: `score_flatten_days`, `score_compute_dtype` — kept
    separate because the measured winner flips between workloads (the
    r05 CPU table: bf16 wins flagship *scoring* while fp32 wins flagship
    *training*). Kernel choice stays the per-shape 'auto' envelope
    (trace-time, zero runtime cost) unless a row pins it.

    `provenance` is "measured" (a table row matched) or "default" (the
    conservative per-backend fallback); `source` says where the row came
    from.

    `seeds_per_program` is the fleet knob (train/fleet.py): how many
    independent seeds one training program should batch when a caller
    runs a multi-seed workload (seed sweeps, the k60 parity protocol).
    1 = serial (the conservative default everywhere); raced values come
    from `scripts/autotune_plan.py --fleet` rows (a `"fleet"` block on
    the row — absent on pre-fleet rows, which keep resolving exactly as
    before).

    `lanes_per_program` is the HYPER-fleet knob (ISSUE 12,
    train/fleet.py lane_configs + eval/sweep.grid_sweep): how many
    heterogeneous (lr, kl_weight) config lanes one training program
    should batch when a caller sweeps a hyperparameter grid. 0 means
    "no measured hyper row" — grid callers then fall back to
    `seeds_per_program` (the lane axis is the same stacked axis), and
    1 means serial. Raced by `scripts/autotune_plan.py --hyper` (a
    `"hyper"` block: `{"lanes_per_program": n}`; absent on every
    pre-ISSUE-12 row, which resolves to 0 — the established
    fleet/stream/obs/mesh backward-compatibility rule).

    `panel_residency` / `stream_chunk_days` are the out-of-core knobs
    (data/stream.py, docs/streaming.md): "hbm" keeps the whole panel on
    device (today's path), "stream" keeps it host-resident and
    double-buffers prefetched day-chunks of `stream_chunk_days` days —
    bitwise-equal results. Raced values come from
    `scripts/autotune_plan.py --stream` rows (a `"stream"` block;
    absent on pre-stream rows, which resolve to "hbm" — no schema
    break).

    `obs_probes` is the observability knob (obs/probes.py via
    TrainConfig.obs_probes): whether the on-device health probes
    compile into the epoch scan. Off by default (the bitwise-neutral
    path); a row's `"obs"` block (`{"probes": true}`) can switch a
    deployment on once `bench.py --obs` has shown the overhead
    acceptable for that shape. Rows without the block keep resolving
    probes-off — same backward-compatibility rule as `fleet`/`stream`.

    `mesh_data_axis` / `mesh_stock_axis` are the mesh-shape knob
    (parallel/mesh.py MeshConfig): how a `--mesh` run should factor the
    visible devices into (data x stock). 0/0 means "no measured mesh
    row" — the run keeps whatever MeshConfig it was given (the
    conservative default everywhere; rows without a `"mesh"` block —
    every pre-PR-6 table — keep resolving exactly as before). Raced
    values come from `scripts/autotune_plan.py --mesh` rows (a `"mesh"`
    block: `{"data_axis": D, "stock_axis": S, "days_per_step": B}`).
    `mesh_days_per_step` is the day batch the mesh winner was RACED at
    (serial day-dp needs days_per_step % data_axis == 0, so the race
    scales it; compose.compatible_days_per_step) — apply_plan applies
    it together with the mesh shape, keeping the persisted row
    self-consistent: a mesh block whose shape needs dps=2 must not ship
    next to the train race's dps=1.

    `serve_precision` is the SERVING knob (serve/registry.py, ISSUE 8):
    which rung of the precision ladder — "float32" (bitwise the
    eval/predict scan path), "bfloat16" (activation cast) or "int8"
    (per-channel weight quantization, ops/quant.py) — a scoring-service
    registry entry of this shape should serve at. Raced by
    `scripts/autotune_plan.py --serve` (a `"serve"` block:
    `{"precision": ...}`; a non-f32 rung only wins when its measured
    rank fidelity vs float32 clears the documented floor). Rows without
    the block resolve to "float32" — the conservative, bitwise default,
    same backward-compatibility rule as fleet/stream/obs/mesh.

    `serve_tick_ms` / `serve_max_tick_batch` are the continuous-batching
    SCHEDULER knobs (serve/daemon.TickScheduler, ISSUE 15): how long a
    worker's cross-tick scheduler holds an under-full batch open for
    late arrivals (trading p50 for fused-dispatch QPS under load), and
    how many requests one tick may fuse. Raced by
    `scripts/autotune_plan.py --serve` under a closed-loop concurrent
    client load (the same `"serve"` block: `{"tick_ms": ...,
    "max_tick_batch": ...}`). tick_ms = -1 / max_tick_batch = 0 mean
    "no measured scheduler row" — the serving CLI then falls back to
    its own defaults; a MEASURED 0ms window (immediate dispatch, a
    legitimate low-concurrency winner) resolves as exactly 0. Rows
    without the keys (every pre-ISSUE-15 table) keep resolving exactly
    as before.

    `serve_slo_ms` / `serve_hedge_ms` are the MULTI-HOST serving knobs
    (serve/router.py + serve/autoscale.py, ISSUE 17): the declared
    per-request latency SLO the autoscaler's control loop holds the
    fleet's observed p99 against, and the hedge delay after which the
    router duplicates a still-unanswered forward to its second
    rendezvous candidate (first answer wins). slo_ms = 0 means "no
    declared SLO" (the autoscaler falls back to queue-depth-only
    signals); hedge_ms = -1 means "no measured hedge row" — the router
    then derives the delay from its own measured latency quantile — and
    a PRESENT 0 is a measured hedge-immediately winner that must
    survive, exactly the serve_tick_ms explicit-None rule. Rows without
    the keys (every pre-ISSUE-17 table) keep resolving exactly as
    before.

    `train_remat` is the rematerialization knob (ISSUE 17 satellite of
    ROADMAP item 4, train/loop.py `jax.checkpoint` wrapping via
    `TrainConfig.remat`): "none" | "dots" | "full". `bench.py --mixed`
    measures the peak_bytes cut per rung (23.9% at "dots" on the
    flagship shape); a row's `"train_remat"` block (`{"remat": ...}`)
    persists the rung once a rig shows a wall-clock or batch-size win.
    "" means "no measured verdict": apply_plan leaves
    `TrainConfig.remat` alone, so every pre-ISSUE-17 row resolves
    exactly as before — the same rule as `train_precision`.

    `kernel_gru` / `kernel_attention` are the MEASURED kernel verdicts
    (ISSUE 19, closing ROADMAP item 3): "pallas" | "xla", raced
    forward+backward against the XLA scan/einsum paths at the row's
    shape on the row's backend by `scripts/autotune_plan.py --kernels`
    (the raced walls persist in the row's `measured.kernels` block for
    audit). A row's `"kernels"` block (`{"gru": ..., "attention": ...}`)
    both sets these provenance fields AND pins `use_pallas_*` to the
    winner, so `apply_plan` ships the measured choice; the
    `pallas_*_wins` predicates read the verdict first and only fall
    back to the frozen round-2 envelope constants when it is "" — which
    is exactly what every pre-ISSUE-19 row (no block) resolves to, so
    existing tables keep resolving through today's static envelope
    unchanged (no schema break). XLA is always in the raced candidate
    set, so a persisted verdict can never regress a shape below the
    fallback path.

    `train_compute_dtype` is the TRAINING-precision knob (ISSUE 16,
    train/state.py resolve_train_dtype, docs/precision.md): which rung
    of the TRAINING ladder — "float32" (the bitwise oracle) or
    "bfloat16" (mixed master-weight path: f32 masters + one bf16
    compute cast + dynamic loss scaling) — a training run of this shape
    should use. Raced by `scripts/autotune_plan.py --train_precision`
    (a `"train_precision"` block: `{"precision": ...}`; a bf16 rung
    only persists when its trained model's masked-Spearman Rank-IC vs
    the f32 oracle clears the documented floor — the same discipline as
    `serve_precision`). "" means "no measured verdict": apply_plan then
    leaves `TrainConfig.compute_dtype` alone (None — it inherits the
    model dtype), so every pre-ISSUE-16 row resolves exactly as before.

    `budget_*` are the OBSERVABILITY envelopes (ISSUE 7): a row's
    optional `"budgets"` block (`{"compile_seconds": s,
    "peak_hbm_bytes": b, "comm_bytes_per_epoch": c}`) states what a
    deployment of this shape is allowed to cost — obs.report flags a
    RUN.jsonl `compile` record past the compile/HBM envelopes
    (`compile_over_budget` / `hbm_over_budget`), `bench.py --mesh`
    judges each cell's comms bill against the comm envelope
    (`comm_over_budget` on the cell — the bill exists where programs
    are compiled per mesh shape), and a serving registry can budget
    admission on them. 0 means "no envelope" (every pre-ISSUE-7 row):
    budgets are opt-in, never inferred.
    """

    flatten_days: bool
    days_per_step: int
    compute_dtype: str
    score_flatten_days: bool
    score_compute_dtype: str
    pad_target: int
    provenance: str
    source: str
    use_pallas_attention: Union[bool, str] = "auto"
    use_pallas_gru: Union[bool, str] = "auto"
    kernel_gru: str = ""
    kernel_attention: str = ""
    seeds_per_program: int = 1
    lanes_per_program: int = 0
    panel_residency: str = "hbm"
    stream_chunk_days: int = 32
    obs_probes: bool = False
    serve_precision: str = "float32"
    train_compute_dtype: str = ""
    train_remat: str = ""
    serve_tick_ms: float = -1.0
    serve_max_tick_batch: int = 0
    serve_slo_ms: float = 0.0
    serve_hedge_ms: float = -1.0
    mesh_data_axis: int = 0
    mesh_stock_axis: int = 0
    mesh_days_per_step: int = 0
    budget_compile_s: float = 0.0
    budget_peak_hbm_bytes: int = 0
    budget_comm_bytes_per_epoch: int = 0

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def describe(self, shape: Optional[ShapeKey] = None,
                 platform: Optional[str] = None,
                 forced: Optional[dict] = None) -> dict:
        """JSON-ready observability block (bench.py `plan`): chosen knobs
        + provenance, plus the trace-time kernel resolution for the
        given shape (what 'auto' will actually pick)."""
        d = self.to_dict()
        if shape is not None:
            on_tpu = (platform_kind(platform) == "tpu")
            # flattened layouts feed the GRU B*N_pad rows per matmul
            gru_rows = (self.pad_target * self.days_per_step
                        if self.flatten_days else self.pad_target)
            d["kernels_resolved"] = {
                "attention": resolve(
                    self.use_pallas_attention,
                    pallas_attention_wins(self.pad_target, shape.hidden_size,
                                          shape.num_factors, on_tpu=on_tpu,
                                          verdict=self.kernel_attention)),
                "gru": resolve(
                    self.use_pallas_gru,
                    pallas_gru_wins(gru_rows, shape.seq_len,
                                    shape.hidden_size, on_tpu=on_tpu,
                                    verdict=self.kernel_gru)),
            }
        if forced:
            d["forced"] = {k: v for k, v in forced.items() if v}
        return d


# ---------------------------------------------------------------------------
# Pad policy (scale-aware, per config — not a global pad_multiple)
# ---------------------------------------------------------------------------


def pad_target_policy(n_stocks: int, platform: Optional[str] = None,
                      shard: int = 1) -> int:
    """Cross-section pad target for a real width of `n_stocks`.

    The quantum is the platform's row-tiling need — 8 rows on TPU (the
    sublane tile; the round-2 flagship measured 356 -> 360 with bf16 at
    exactly this quantum), 4 on hosts (SIMD width; the r05 CPU
    head-to-head measured at 4) — times whatever the 'stock' mesh axis
    needs for even sharding. CSI800 pads 800 -> 800 under this policy
    instead of the fixed 1024 (28% dead compute) the old preset forced.
    """
    q = 8 if platform_kind(platform) == "tpu" else 4
    q = math.lcm(q, max(1, shard))
    return ((n_stocks + q - 1) // q) * q


def platform_kind(platform: Optional[str] = None) -> str:
    """Normalize a platform label ('tpu-v5e', 'TPU', jax backend names)
    to the table's platform key: 'tpu' | 'gpu' | 'cpu'."""
    if platform is None:
        import jax

        platform = jax.default_backend()
    p = str(platform).lower()
    if p.startswith("tpu"):
        return "tpu"
    if p.startswith(("gpu", "cuda", "rocm")):
        return "gpu"
    return "cpu"


# ---------------------------------------------------------------------------
# Envelope table
# ---------------------------------------------------------------------------

PLAN_TABLE_ENV = "FACTORVAE_PLAN_TABLE"
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_TABLE_PATH = os.path.join(_REPO_ROOT, "PLAN_TABLE.json")

# Builtin measured rows. The TPU flagship row encodes the round-2 v5e
# measurement behind PERF.md's 35.3x headline — bench.py on a live chip
# must keep resolving to exactly these knobs (and the policy pad
# 356 -> 360) so the next relay round reproduces that row unchanged.
_BUILTIN_ROWS: list = [
    {
        "platform": "tpu",
        "shape": {"c": 158, "t": 20, "h": 64, "k": 96, "m": 128},
        "n_min": 300, "n_max": 360,
        "train": {"flatten_days": True, "days_per_step": 8,
                  "compute_dtype": "bfloat16"},
        "score": {"flatten_days": True, "compute_dtype": "bfloat16"},
        "source": "PERF.md 'Measured (round 2)' live v5e: bf16 dps=8 "
                  "flattened flagship, 1,057,841 w/s (35.3x)",
    },
]


def table_path(path: Optional[str] = None) -> str:
    return path or os.environ.get(PLAN_TABLE_ENV) or DEFAULT_TABLE_PATH


def _read_rows(path: str) -> list:
    """Rows from a table file; [] on a missing/corrupt/mis-shaped file
    (same tolerance for all three: the planner falls back, it never
    crashes on table state). Non-dict entries are dropped."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return []
    rows = data.get("rows", []) if isinstance(data, dict) else data
    if not isinstance(rows, list):
        return []
    return [r for r in rows if isinstance(r, dict)]


def load_table(path: Optional[str] = None) -> list:
    """File rows (freshest measurements) first, then builtins."""
    return _read_rows(table_path(path)) + _BUILTIN_ROWS


def _row_key(row: dict) -> tuple:
    s = row.get("shape", {})
    return (row.get("platform"), s.get("c"), s.get("t"), s.get("h"),
            s.get("k"), s.get("m"), row.get("n_min"), row.get("n_max"))


def _envelopes_overlap(a: dict, b: dict) -> bool:
    """True when two rows cover the same (platform, shape) and their
    [n_min, n_max] width envelopes intersect."""
    if (a.get("platform"), a.get("shape")) != (b.get("platform"),
                                              b.get("shape")):
        return False
    try:
        return a["n_min"] <= b["n_max"] and b["n_min"] <= a["n_max"]
    except (KeyError, TypeError):
        return False


def save_rows(new_rows: Sequence[dict], path: Optional[str] = None) -> str:
    """Merge measured rows into the persisted table. An existing row
    whose envelope OVERLAPS a new row's (same platform+shape) is
    dropped, not just an exact [n_min, n_max] match — otherwise a stale
    merged row (say [300, 356]) would survive a re-measurement that
    wrote per-width rows (300 and 356 separately) and, matching first,
    shadow the fresh measurements forever. Non-overlapping rows are
    kept. Builtin rows are never written out — they live in code."""
    p = table_path(path)
    existing = _read_rows(p)
    merged = {_row_key(r): r for r in existing
              if not any(_envelopes_overlap(r, n) for n in new_rows)}
    for r in new_rows:
        merged[_row_key(r)] = r
    with open(p, "w") as f:
        json.dump({"rows": sorted(merged.values(),
                                  key=lambda r: json.dumps(_row_key(r)))},
                  f, indent=1, sort_keys=True)
        f.write("\n")
    return p


def _match(row: dict, shape: ShapeKey, platform: str) -> bool:
    if row.get("platform") != platform:
        return False
    s = row.get("shape", {})
    if (s.get("c"), s.get("t"), s.get("h"), s.get("k"), s.get("m")) != (
            shape.num_features, shape.seq_len, shape.hidden_size,
            shape.num_factors, shape.num_portfolios):
        return False
    # The envelope is mandatory: a row without an explicit measured
    # [n_min, n_max] must not match ANY width (defaulting it to the
    # queried width would make a hand-edited row match everything —
    # exactly the extrapolation the envelope rule forbids).
    if "n_min" not in row or "n_max" not in row:
        return False
    return row["n_min"] <= shape.n_stocks <= row["n_max"]


# ---------------------------------------------------------------------------
# Planning
# ---------------------------------------------------------------------------

_CPU_DEFAULT = {"flatten_days": False, "days_per_step": 1,
                "compute_dtype": "float32"}
_TPU_DEFAULT = {"flatten_days": True, "days_per_step": 8,
                "compute_dtype": "bfloat16"}


def plan_for(shape: ShapeKey, platform: Optional[str] = None,
             table: Optional[Sequence[dict]] = None, shard: int = 1,
             table_path_: Optional[str] = None) -> Plan:
    """Resolve the execution plan for (platform, shape).

    A measured row inside its [n_min, n_max] envelope wins; otherwise
    the conservative per-backend default — reference-faithful
    dps=1 un-flattened float32 on CPU/GPU hosts, the round-2-measured
    winners (dps=8 flattened bf16) on TPU. Deterministic: same inputs,
    same Plan.
    """
    plat = platform_kind(platform)
    rows = list(table) if table is not None else load_table(table_path_)
    for row in rows:
        if _match(row, shape, plat):
            train = row.get("train", {})
            score = row.get("score", train)
            # A row-pinned pad_target was measured at shard=1; re-align
            # it to this run's platform x stock-shard quantum so an
            # uneven mesh split never ships (e.g. row pad 800 under a
            # 3-way stock axis -> 816, not 800).
            pad = pad_target_policy(
                max(shape.n_stocks, int(row.get("pad_target") or 0)),
                plat, shard)
            # Pre-ISSUE-19 rows have no "kernels" block: "" = no
            # measured kernel verdict, and use_pallas_* stays at the
            # row's own pin or "auto" (the static round-2 envelope) —
            # no schema break. A measured block pins the winner; an
            # EXPLICIT row-level use_pallas_* key still outranks it
            # (a hand pin is a deliberate override of the race).
            kern = row.get("kernels") or {}
            return Plan(
                flatten_days=bool(train.get("flatten_days", False)),
                days_per_step=int(train.get("days_per_step", 1)),
                compute_dtype=str(train.get("compute_dtype", "float32")),
                score_flatten_days=bool(score.get(
                    "flatten_days", train.get("flatten_days", False))),
                score_compute_dtype=str(score.get(
                    "compute_dtype", train.get("compute_dtype", "float32"))),
                pad_target=pad,
                provenance="measured",
                source=str(row.get("source", "plan table")),
                use_pallas_attention=row.get(
                    "use_pallas_attention",
                    (kern.get("attention") == "pallas")
                    if kern.get("attention") else "auto"),
                use_pallas_gru=row.get(
                    "use_pallas_gru",
                    (kern.get("gru") == "pallas")
                    if kern.get("gru") else "auto"),
                kernel_gru=str(kern.get("gru") or ""),
                kernel_attention=str(kern.get("attention") or ""),
                # Pre-fleet rows have no "fleet" block: resolve to the
                # serial default (no schema break for existing tables).
                seeds_per_program=int(
                    (row.get("fleet") or {}).get("seeds_per_program") or 1),
                # Pre-ISSUE-12 rows have no "hyper" block: 0 = no
                # measured lane width (grid callers fall back to
                # seeds_per_program; same no-schema-break rule).
                lanes_per_program=int(
                    (row.get("hyper") or {}).get("lanes_per_program") or 0),
                # Pre-stream rows have no "stream" block: resolve to the
                # HBM residency (same backward-compatibility rule).
                panel_residency=str(
                    (row.get("stream") or {}).get("panel_residency")
                    or "hbm"),
                stream_chunk_days=int(
                    (row.get("stream") or {}).get("chunk_days") or 32),
                # Pre-observatory rows have no "obs" block: probes off
                # (the bitwise-neutral default).
                obs_probes=bool(
                    (row.get("obs") or {}).get("probes", False)),
                # Pre-ISSUE-8 rows have no "serve" block: float32 (the
                # bitwise-vs-predict.py default) — precision downgrades
                # are measured wins, never inferred.
                serve_precision=str(
                    (row.get("serve") or {}).get("precision")
                    or "float32"),
                # Pre-ISSUE-16 rows have no "train_precision" block:
                # "" = no measured training-precision verdict (the
                # TrainConfig dtype stays None and inherits the model
                # dtype — same no-schema-break rule).
                train_compute_dtype=str(
                    (row.get("train_precision") or {}).get("precision")
                    or ""),
                # Pre-ISSUE-17 rows have no "train_remat" block: "" =
                # no measured remat verdict (TrainConfig.remat keeps
                # its own default — same no-schema-break rule).
                train_remat=str(
                    (row.get("train_remat") or {}).get("remat") or ""),
                # Pre-ISSUE-15 serve blocks carry no scheduler keys:
                # -1/0 = no measured scheduler row (the serving CLI
                # falls back to its own defaults). A PRESENT tick_ms
                # of 0 is a measured immediate-dispatch winner and
                # must survive — `or` would collapse it into the
                # sentinel.
                serve_tick_ms=(
                    float((row.get("serve") or {})["tick_ms"])
                    if (row.get("serve") or {}).get("tick_ms")
                    is not None else -1.0),
                serve_max_tick_batch=int(
                    (row.get("serve") or {}).get("max_tick_batch")
                    or 0),
                # Pre-ISSUE-17 serve blocks carry no multi-host keys:
                # slo_ms=0 = no declared SLO; hedge_ms=-1 = no measured
                # hedge delay (the router derives it from its own
                # latency quantile). A PRESENT hedge_ms of 0 is a
                # measured hedge-immediately winner and must survive —
                # same explicit-None rule as tick_ms.
                serve_slo_ms=float(
                    (row.get("serve") or {}).get("slo_ms") or 0.0),
                serve_hedge_ms=(
                    float((row.get("serve") or {})["hedge_ms"])
                    if (row.get("serve") or {}).get("hedge_ms")
                    is not None else -1.0),
                # Pre-PR-6 rows have no "mesh" block: 0/0 = keep the
                # run's own MeshConfig (no schema break).
                mesh_data_axis=int(
                    (row.get("mesh") or {}).get("data_axis") or 0),
                mesh_stock_axis=int(
                    (row.get("mesh") or {}).get("stock_axis") or 0),
                mesh_days_per_step=int(
                    (row.get("mesh") or {}).get("days_per_step") or 0),
                # Pre-ISSUE-7 rows have no "budgets" block: 0 = no
                # envelope (budgets are opt-in, same rule as
                # fleet/stream/obs/mesh).
                budget_compile_s=float(
                    (row.get("budgets") or {}).get("compile_seconds")
                    or 0.0),
                budget_peak_hbm_bytes=int(
                    (row.get("budgets") or {}).get("peak_hbm_bytes") or 0),
                budget_comm_bytes_per_epoch=int(
                    (row.get("budgets") or {}).get("comm_bytes_per_epoch")
                    or 0),
            )
    default = _TPU_DEFAULT if plat == "tpu" else _CPU_DEFAULT
    src = ("per-backend default: round-2 measured TPU winners (PERF.md)"
           if plat == "tpu" else
           "per-backend default: reference-faithful CPU path (dps=1, "
           "un-flattened, float32)")
    return Plan(
        flatten_days=default["flatten_days"],
        days_per_step=default["days_per_step"],
        compute_dtype=default["compute_dtype"],
        score_flatten_days=default["flatten_days"],
        score_compute_dtype=default["compute_dtype"],
        pad_target=pad_target_policy(shape.n_stocks, plat, shard),
        provenance="default",
        source=src,
    )


def shape_of(config, n_stocks: int) -> ShapeKey:
    """ShapeKey from a Config (or ModelConfig) + real cross-section."""
    m = getattr(config, "model", config)
    return ShapeKey(
        num_features=m.num_features, seq_len=m.seq_len,
        hidden_size=m.hidden_size, num_factors=m.num_factors,
        num_portfolios=m.num_portfolios, n_stocks=int(n_stocks),
    )


def plan_for_config(config, n_stocks: int, platform: Optional[str] = None,
                    shard: int = 1,
                    table: Optional[Sequence[dict]] = None) -> Plan:
    return plan_for(shape_of(config, n_stocks), platform=platform,
                    table=table, shard=shard)


def apply_plan(config, plan: Plan, *, keep_days_per_step: bool = False,
               keep_dtype: bool = False, keep_layout: bool = False,
               keep_pad: bool = False, keep_kernels: bool = False,
               keep_residency: bool = False, keep_obs: bool = False,
               keep_mesh: bool = False, keep_remat: bool = False):
    """Return a Config with the plan's TRAINING knobs applied. `keep_*`
    leaves an explicitly user-set knob alone (CLI flag precedence)."""
    model_kw: dict = {}
    if not keep_dtype:
        model_kw["compute_dtype"] = plan.compute_dtype
    if not keep_layout:
        model_kw["flatten_days"] = plan.flatten_days
    if not keep_kernels:
        # Usually "auto" (the per-shape raced envelope), but a table row
        # may pin a kernel on/off — the pin must reach the model, or the
        # logged plan block would disagree with what actually ran.
        model_kw["use_pallas_attention"] = plan.use_pallas_attention
        model_kw["use_pallas_gru"] = plan.use_pallas_gru
    model = dataclasses.replace(config.model, **model_kw) \
        if model_kw else config.model
    apply_mesh = (not keep_mesh and plan.mesh_data_axis > 0
                  and plan.mesh_stock_axis > 0)
    train_kw: dict = {}
    if not keep_days_per_step:
        # A mesh row's winner was raced at its OWN (scaled) day batch —
        # serial day-dp requires days_per_step % data_axis == 0 — so
        # applying the mesh shape without its days_per_step would ship
        # a self-incompatible config (compose.validate would reject it
        # at Trainer construction).
        train_kw["days_per_step"] = (
            plan.mesh_days_per_step
            if apply_mesh and plan.mesh_days_per_step > 0
            else plan.days_per_step)
    if not keep_dtype and plan.train_compute_dtype:
        # A measured training-precision verdict (ISSUE 16): the rung
        # autotune raced past the Rank-IC floor. Absent ("") the
        # TrainConfig dtype stays None — it inherits the model dtype
        # through resolve_train_dtype, exactly the pre-ISSUE-16 path.
        train_kw["compute_dtype"] = plan.train_compute_dtype
    if not keep_remat and plan.train_remat:
        # A measured remat verdict (ISSUE 17 satellite): the rung a rig
        # raced to a wall-clock/batch-size win. Absent ("") the
        # TrainConfig.remat default stands — every pre-ISSUE-17 row
        # resolves exactly as before.
        train_kw["remat"] = plan.train_remat
    if not keep_obs:
        train_kw["obs_probes"] = plan.obs_probes
    train = dataclasses.replace(config.train, **train_kw) \
        if train_kw else config.train
    data_kw: dict = {}
    if not keep_pad:
        data_kw["max_stocks"] = plan.pad_target
    if not keep_residency:
        data_kw["panel_residency"] = plan.panel_residency
        data_kw["stream_chunk_days"] = plan.stream_chunk_days
    data = dataclasses.replace(config.data, **data_kw) \
        if data_kw else config.data
    mesh_cfg = config.mesh
    if apply_mesh:
        # A measured mesh row reshapes the (data x stock) factorization;
        # 0/0 rows (every pre-PR-6 table) leave MeshConfig alone.
        mesh_cfg = dataclasses.replace(
            config.mesh, data_axis=plan.mesh_data_axis,
            stock_axis=plan.mesh_stock_axis)
    return dataclasses.replace(config, model=model, train=train, data=data,
                               mesh=mesh_cfg)


# ---------------------------------------------------------------------------
# Persistent XLA compilation cache (ISSUE 8)
# ---------------------------------------------------------------------------

COMPILE_CACHE_ENV = "FACTORVAE_COMPILE_CACHE"


def setup_compilation_cache(path: Optional[str] = None,
                            min_compile_secs: float = 0.0) -> Optional[str]:
    """Point jax at a persistent on-disk compilation cache so daemon
    restarts, autotune races and repeated CLI runs stop paying
    recompiles of programs XLA has already built.

    Resolution: explicit `path` > the `FACTORVAE_COMPILE_CACHE` env var
    > disabled (returns None). `path="off"` disables explicitly (the
    CLI's documented opt-out even when the env var is set). Returns the
    absolute cache dir when enabled, else None — callers log it.

    `min_compile_secs=0.0` (the serving default) caches EVERY program:
    a scoring daemon's whole value is its warm restart, and the small
    per-entry disk cost is the price of zero `compile` records on the
    second process (tests/test_serve.py pins exactly that). Training
    CLIs may pass a higher floor to keep the cache to the expensive
    epoch programs. No-op (None) on jax versions without the flags.
    """
    p = path or os.environ.get(COMPILE_CACHE_ENV)
    if not p or p == "off":
        return None
    import jax

    p = os.path.abspath(p)
    try:
        os.makedirs(p, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", p)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_secs))
    except Exception:
        return None
    return p


def score_model_config(model_cfg, plan: Plan):
    """ModelConfig with the plan's SCORING knobs applied (safe on the
    same params: compute_dtype only casts activations and flatten_days
    keeps an identical parameter tree — tested interchangeable)."""
    return dataclasses.replace(
        model_cfg,
        compute_dtype=plan.score_compute_dtype,
        flatten_days=plan.score_flatten_days,
    )
