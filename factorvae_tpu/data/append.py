"""Incremental panel store: append-only day slabs + a sha256 manifest.

The walk-forward loop (factorvae_tpu/wf, ROADMAP item 2) ingests one
new trading day per cycle. Re-pickling the whole history to add a day
— the only path the pickle loader offers — is both slow and a crash
hazard (a kill mid-write corrupts the single file the entire run
depends on). This module stores the panel as an APPEND-ONLY sequence
of day slabs instead:

    <dir>/MANIFEST.json          instruments + ordered slab records
    <dir>/slabs/slab_00001.npz   values (I, D_s, C+1) f32,
                                 valid (D_s, I) bool, dates int64[ns]

Crash discipline (the chaos classes `kill_mid_append` /
`corrupt_append_slab` exercise exactly these windows):

- A slab lands via tmp-write + atomic rename, then is RE-READ and
  sha256-verified against the digest of the bytes we meant to write
  BEFORE the manifest commit — torn or corrupted slab bytes abort the
  append (`AppendError`) with the manifest untouched, so the store
  never references data it cannot vouch for.
- The manifest itself commits by tmp-write + atomic rename. A kill
  between slab rename and manifest commit leaves an ORPHAN slab file
  the next append of the same days simply overwrites — re-running a
  killed append is idempotent.
- Appending days the manifest already ends with is a verified no-op
  returning the existing slab record (the resume path of a cycle whose
  journal commit raced a crash); any other overlap is a loud error.

Readers get the whole history as one dense `Panel` via `load_panel`
(optionally verifying every slab), while an in-memory consumer that
already holds the previous panel only needs the NEW slab —
`PanelDataset.extend_days` (data/loader.py) is that consumer: the
stream-residency serving path picks up appended days with no full
reload and no device transfer.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
from typing import List, Optional

import numpy as np
import pandas as pd

from factorvae_tpu.data.panel import Panel
from factorvae_tpu.utils.logging import timeline_event

MANIFEST_NAME = "MANIFEST.json"
SLAB_DIRNAME = "slabs"


class AppendError(RuntimeError):
    """Append/validation failure with a one-line actionable message."""


def _sha256_bytes(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        for block in iter(lambda: fh.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def _slab_bytes(values: np.ndarray, valid: np.ndarray,
                dates: pd.DatetimeIndex) -> bytes:
    """Serialize one slab to npz bytes (deterministic for fixed
    inputs: uncompressed, fixed key order)."""
    buf = io.BytesIO()
    np.savez(buf,
             values=np.asarray(values, np.float32),
             valid=np.asarray(valid, bool),
             dates=np.asarray(pd.DatetimeIndex(dates).asi8, np.int64))
    return buf.getvalue()


def _read_slab(path: str):
    with np.load(path) as z:
        return (z["values"], z["valid"],
                pd.DatetimeIndex(z["dates"].astype("datetime64[ns]")))


def align_to_instruments(piece: Panel, instruments: np.ndarray) -> Panel:
    """Reindex a panel piece onto the store's instrument axis: missing
    instruments become invalid NaN rows; instruments the store has
    never seen are rejected (cross-section growth means a new n_max,
    new padding and a retrain — not an append)."""
    store_inst = np.asarray(instruments)
    piece_inst = np.asarray(piece.instruments)
    unknown = sorted(set(piece_inst) - set(store_inst))
    if unknown:
        raise AppendError(
            f"appended panel brings {len(unknown)} instrument(s) the "
            f"store has never seen (first: {unknown[0]!r}); the "
            f"cross-section axis is fixed at store creation — rebuild "
            f"the store to widen it")
    if piece_inst.shape == store_inst.shape and (
            piece_inst == store_inst).all():
        return piece
    pos = {str(n): i for i, n in enumerate(piece_inst)}
    d = piece.num_days
    c = piece.values.shape[-1]
    values = np.full((len(store_inst), d, c), np.nan, np.float32)
    valid = np.zeros((d, len(store_inst)), bool)
    for j, name in enumerate(store_inst):
        i = pos.get(str(name))
        if i is not None:
            values[j] = piece.values[i]
            valid[:, j] = piece.valid[:, i]
    return Panel(values=values, valid=valid, dates=piece.dates,
                 instruments=store_inst)


class PanelStore:
    """Append-only slab store over one panel history (module docstring
    has the layout and crash discipline)."""

    def __init__(self, directory: str):
        self.directory = os.path.abspath(directory)
        path = os.path.join(self.directory, MANIFEST_NAME)
        try:
            with open(path) as fh:
                self._manifest = json.load(fh)
        except FileNotFoundError:
            raise AppendError(
                f"no panel store at {self.directory} (missing "
                f"{MANIFEST_NAME}); create one with "
                f"PanelStore.create(dir, panel)") from None
        except ValueError as e:
            raise AppendError(
                f"panel store manifest {path} is corrupt ({e}); the "
                f"slabs are intact — rebuild the manifest or restore "
                f"it from backup") from None

    # ---- creation --------------------------------------------------------

    @classmethod
    def create(cls, directory: str, panel: Panel) -> "PanelStore":
        """Initialize a store from a seed panel (slab 1 = its full
        history). Refuses to clobber a store that already holds data —
        but an EMPTY store (manifest committed, zero slabs: the crash
        window between the manifest commit and the seed-slab append)
        is adopted and seeded, so a killed create() re-runs instead of
        wedging the directory forever."""
        directory = os.path.abspath(directory)
        if os.path.exists(os.path.join(directory, MANIFEST_NAME)):
            existing = cls(directory)
            if existing.generation > 0:
                raise AppendError(
                    f"panel store already exists at {directory}; open "
                    f"it with PanelStore(dir) and append instead")
            existing.append_panel(panel)
            return existing
        os.makedirs(os.path.join(directory, SLAB_DIRNAME), exist_ok=True)
        manifest = {
            "version": 1,
            "instruments": [str(n) for n in panel.instruments],
            "num_columns": int(panel.values.shape[-1]),
            "slabs": [],
        }
        tmp = os.path.join(directory, MANIFEST_NAME + ".tmp")
        with open(tmp, "w") as fh:
            json.dump(manifest, fh)
        os.replace(tmp, os.path.join(directory, MANIFEST_NAME))
        store = cls(directory)
        store.append_panel(panel)
        return store

    # ---- facts -----------------------------------------------------------

    @property
    def generation(self) -> int:
        """Number of committed slabs (the walk-forward cycle anchor)."""
        return len(self._manifest["slabs"])

    @property
    def instruments(self) -> np.ndarray:
        return np.asarray(self._manifest["instruments"])

    @property
    def num_columns(self) -> int:
        """Feature columns + 1 label column (fixed at creation)."""
        return int(self._manifest["num_columns"])

    @property
    def slabs(self) -> List[dict]:
        return list(self._manifest["slabs"])

    @property
    def num_days(self) -> int:
        return sum(int(s["num_days"]) for s in self._manifest["slabs"])

    @property
    def end_date(self) -> Optional[pd.Timestamp]:
        if not self._manifest["slabs"]:
            return None
        return pd.Timestamp(self._manifest["slabs"][-1]["end"])

    def _slab_path(self, name: str) -> str:
        return os.path.join(self.directory, SLAB_DIRNAME, name)

    # ---- append ----------------------------------------------------------

    def _commit_manifest(self) -> None:
        path = os.path.join(self.directory, MANIFEST_NAME)
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(self._manifest, fh, indent=1)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)

    def append_panel(self, piece: Panel) -> dict:
        """Append a panel piece as one new slab; returns its manifest
        record. Validated before commit (module docstring); idempotent
        when `piece` is exactly the days the store already ends with."""
        from factorvae_tpu import chaos

        piece = align_to_instruments(piece, self.instruments)
        if int(piece.values.shape[-1]) != self._manifest["num_columns"]:
            raise AppendError(
                f"appended panel has {piece.values.shape[-1]} columns; "
                f"the store was created with "
                f"{self._manifest['num_columns']} — feature schema is "
                f"fixed at store creation")
        if piece.num_days == 0:
            raise AppendError("appended panel has zero days")
        end = self.end_date
        if end is not None and piece.dates[0] <= end:
            last = self._manifest["slabs"][-1]
            if (str(piece.dates[0].date()) == last["start"]
                    and str(piece.dates[-1].date()) == last["end"]
                    and piece.num_days == last["num_days"]):
                # Idempotent re-append (a resumed cycle whose journal
                # commit raced a crash): verify the committed slab
                # carries these exact bytes and return its record.
                data = _slab_bytes(piece.values, piece.valid, piece.dates)
                if _sha256_file(self._slab_path(last["name"])) \
                        != _sha256_bytes(data):
                    raise AppendError(
                        f"re-appended days [{last['start']}, "
                        f"{last['end']}] differ from the committed slab "
                        f"{last['name']} — same dates, different bytes; "
                        f"the incoming feed is not deterministic")
                return dict(last)
            raise AppendError(
                f"appended days start at {piece.dates[0].date()} but "
                f"the store already ends at {end.date()}; appends must "
                f"be strictly newer (or exactly the final slab, for "
                f"idempotent resume)")

        name = f"slab_{self.generation + 1:05d}.npz"
        record = {
            "name": name,
            "num_days": int(piece.num_days),
            "start": str(piece.dates[0].date()),
            "end": str(piece.dates[-1].date()),
            "sha256": None,
        }
        # Chaos window 0: killed before any bytes land — re-running the
        # append is a plain rerun.
        if chaos.fault("kill_mid_append", step=0) is not None:
            chaos.ops.kill_now()
        data = _slab_bytes(piece.values, piece.valid, piece.dates)
        record["sha256"] = _sha256_bytes(data)
        final = self._slab_path(name)
        os.makedirs(os.path.dirname(final), exist_ok=True)
        tmp = final + ".tmp"
        with open(tmp, "wb") as fh:
            fh.write(data)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, final)
        # Chaos window 1: slab committed, manifest not — the orphan a
        # re-run overwrites.
        if chaos.fault("kill_mid_append", step=1) is not None:
            chaos.ops.kill_now()
        corrupt = chaos.fault("corrupt_append_slab")
        if corrupt is not None:
            chaos.ops.corrupt_file(final, rng_seed=corrupt.rng_seed)
        # Validation BEFORE commit: re-read the committed file and
        # compare against the digest of the bytes we intended. Torn or
        # corrupted slabs abort with the manifest untouched.
        on_disk = _sha256_file(final)
        if on_disk != record["sha256"]:
            os.remove(final)
            timeline_event("append_slab_rejected", cat="recovery",
                           resource="data", slab=name,
                           expected=record["sha256"], actual=on_disk)
            raise AppendError(
                f"slab {name} failed sha256 validation before commit "
                f"(wrote {record['sha256'][:12]}…, read back "
                f"{on_disk[:12]}…); the slab was removed and the "
                f"manifest is untouched — retry the append")
        self._manifest["slabs"].append(record)
        self._commit_manifest()
        timeline_event("append_slab", cat="data", resource="data",
                       slab=name, days=record["num_days"],
                       start=record["start"], end=record["end"])
        return dict(record)

    # ---- read ------------------------------------------------------------

    def verify(self) -> Optional[str]:
        """None when every committed slab's bytes match its manifest
        sha256; otherwise a one-line reason naming the first mismatch."""
        for rec in self._manifest["slabs"]:
            path = self._slab_path(rec["name"])
            if not os.path.exists(path):
                return f"slab missing: {rec['name']}"
            if _sha256_file(path) != rec["sha256"]:
                return f"sha256 mismatch: {rec['name']}"
        return None

    def load_slab(self, record: dict, verify: bool = True) -> Panel:
        """One slab as a Panel on the store's instrument axis."""
        path = self._slab_path(record["name"])
        if verify and _sha256_file(path) != record["sha256"]:
            raise AppendError(
                f"slab {record['name']} failed sha256 verification; "
                f"the store is damaged — restore the slab or rebuild "
                f"from the source feed")
        values, valid, dates = _read_slab(path)
        return Panel(values=values, valid=valid, dates=dates,
                     instruments=self.instruments)

    def load_panel(self, verify: bool = False) -> Panel:
        """The whole history as one dense Panel (slabs concatenated on
        the day axis). `verify=True` sha256-checks every slab first."""
        if not self._manifest["slabs"]:
            raise AppendError(f"panel store {self.directory} is empty")
        if verify:
            bad = self.verify()
            if bad is not None:
                raise AppendError(
                    f"panel store {self.directory} failed verification "
                    f"({bad}); restore the slab or rebuild the store")
        pieces = [_read_slab(self._slab_path(r["name"]))
                  for r in self._manifest["slabs"]]
        values = np.concatenate([p[0] for p in pieces], axis=1)
        valid = np.concatenate([p[1] for p in pieces], axis=0)
        dates = pd.DatetimeIndex(
            np.concatenate([np.asarray(p[2].asi8) for p in pieces])
            .astype("datetime64[ns]"))
        return Panel(values=values, valid=valid, dates=dates,
                     instruments=self.instruments)
