"""Dataset construction (ETL) — qlib Alpha158/Alpha360 -> panel pickle.

Capability parity with reference data/make_dataset.py:1-102: initialize
qlib for the CN or US region, build the Alpha158 handler with the same
processor chain (RobustZScoreNorm+Fillna on features; DropnaLabel+
CSRankNorm on the label; label = Ref($close,-2)/Ref($close,-1)-1,
make_dataset.py:50-58), fetch learn/infer frames and pickle them in the
MultiIndex (datetime, instrument) schema this framework's loader reads.

qlib is an *external tool* here exactly as it is for the reference (it is
not bundled with either framework); this module degrades to a clear
instruction if qlib or its data bundle is absent. Prebuilt pickles from
the reference pipeline load unchanged via `data.load_frame`.
"""

from __future__ import annotations

from typing import Optional


QLIB_RECIPE = """\
qlib is not installed (or its data bundle is missing). To build the panel:

1. Install qlib and download daily data (the reference's recipe,
   data/readme.md):
     pip install pyqlib
     # CN (CSI300/CSI800):
     python -m qlib.run.get_data qlib_data --target_dir ~/.qlib/qlib_data/cn_data --region cn
     # or collect from Yahoo via the qlib scripts collector.
2. Run this module:
     python -m factorvae_tpu.data.etl --region cn --market csi300 \\
         --out ./data/csi_data.pkl
3. Point the trainer at the pickle: python -m factorvae_tpu.cli --dataset ./data/csi_data.pkl
"""


def build_dataset(
    out_path: str,
    region: str = "cn",
    market: str = "csi300",
    start: str = "2008-01-01",
    end: str = "2020-12-31",
    fit_start: str = "2009-01-01",   # reference pins this (make_dataset.py:47)
    fit_end: str = "2017-12-31",
    handler: str = "Alpha158",
    qlib_dir: Optional[str] = None,
    infer_out_path: Optional[str] = None,
) -> str:
    """Build and pickle the feature panel. Returns the pickle path.

    Matches the reference handler config (make_dataset.py:44-59): infer
    processors RobustZScoreNorm(clip, fit on [fit_start, fit_end]) +
    Fillna on features; learn processors DropnaLabel + CSRankNorm on the
    label; label = Ref($close,-2)/Ref($close,-1)-1.
    """
    try:
        import qlib
        from qlib.constant import REG_CN, REG_US
        from qlib.contrib.data.handler import Alpha158, Alpha360
    except ImportError as e:
        raise ImportError(QLIB_RECIPE) from e

    import os

    region = region.lower()
    default_dir = os.path.expanduser(
        f"~/.qlib/qlib_data/{'cn' if region == 'cn' else 'us'}_data"
    )
    qlib.init(
        provider_uri=qlib_dir or default_dir,
        region=REG_CN if region == "cn" else REG_US,
    )

    handler_cls = {"Alpha158": Alpha158, "Alpha360": Alpha360}[handler]
    handler_config = {
        "start_time": start,
        "end_time": end,
        "fit_start_time": fit_start,
        "fit_end_time": fit_end,
        "instruments": market,
        "infer_processors": [
            {
                "class": "RobustZScoreNorm",
                "kwargs": {
                    "fields_group": "feature",
                    "clip_outlier": True,
                    "fit_start_time": fit_start,
                    "fit_end_time": fit_end,
                },
            },
            {"class": "Fillna", "kwargs": {"fields_group": "feature"}},
        ],
        "learn_processors": [
            {"class": "DropnaLabel"},
            {"class": "CSRankNorm", "kwargs": {"fields_group": "label"}},
        ],
        "label": ["Ref($close, -2) / Ref($close, -1) - 1"],
    }
    h = handler_cls(**handler_config)

    from qlib.data.dataset.handler import DataHandlerLP

    learn = h.fetch(col_set=["feature", "label"], data_key=DataHandlerLP.DK_L)
    learn.to_pickle(out_path)
    if infer_out_path:
        infer = h.fetch(col_set=["feature", "label"], data_key=DataHandlerLP.DK_I)
        infer.to_pickle(infer_out_path)
    return out_path


def main(argv=None) -> int:
    import argparse
    import sys

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", default="./data/csi_data.pkl")
    p.add_argument("--infer_out", default=None,
                   help="also write the inference-processed panel (no "
                        "DropnaLabel/CSRankNorm), as the backtest uses")
    p.add_argument("--region", choices=["cn", "us"], default="cn")
    p.add_argument("--market", default="csi300")
    p.add_argument("--handler", choices=["Alpha158", "Alpha360"], default="Alpha158")
    p.add_argument("--start", default="2008-01-01")
    p.add_argument("--end", default="2020-12-31")
    p.add_argument("--fit_start", default="2009-01-01")
    p.add_argument("--fit_end", default="2017-12-31")
    p.add_argument("--qlib_dir", default=None)
    args = p.parse_args(argv)
    try:
        path = build_dataset(
            args.out, region=args.region, market=args.market, start=args.start,
            end=args.end, fit_start=args.fit_start, fit_end=args.fit_end,
            handler=args.handler, qlib_dir=args.qlib_dir,
            infer_out_path=args.infer_out,
        )
    except ImportError as e:
        print(e, file=sys.stderr)
        return 2
    except Exception as e:
        # qlib present but its data bundle / provider is broken or absent:
        # surface the recipe, not a qlib traceback.
        print(f"qlib ETL failed: {type(e).__name__}: {e}\n\n{QLIB_RECIPE}",
              file=sys.stderr)
        return 2
    print(f"wrote {path}")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
