"""Dense panel construction from the reference's pickle schema.

The reference consumes a pandas DataFrame with MultiIndex
``(datetime, instrument)``, 158 Alpha158 feature columns + 1 label column
(reference main.py:36-37 keeps ``.iloc[:, :159]`` and renames the last
column to 'LABEL0'; data/make_dataset.py:66-83 writes the pickle).

TPU-first re-design: instead of a per-sample sampler + Python DataLoader
(reference dataset.py:41-274), the whole panel is densified ONCE into

    values: (I, D, C+1) float32, NaN where an (instrument, day) row is absent
    valid:  (D, I) bool — row exists for that trading day
    dates / instruments: the calendar-grid axes

then windows are *gathered on device* per step via a precomputed
ffill+bfill index map (see windows.py). At CSI300 scale the whole train
split is ~0.5 GB and lives in HBM for the entire run (SURVEY.md §7.5).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np
import pandas as pd


@dataclasses.dataclass
class Panel:
    """A dense (instrument, day, column) view of a stock panel."""

    values: np.ndarray        # (I, D, C+1) float32; [..., -1] is the label
    valid: np.ndarray         # (D, I) bool
    dates: pd.DatetimeIndex   # (D,)
    instruments: np.ndarray   # (I,) str

    @property
    def num_days(self) -> int:
        return len(self.dates)

    @property
    def num_instruments(self) -> int:
        return len(self.instruments)

    @property
    def num_features(self) -> int:
        return self.values.shape[-1] - 1

    def date_slice(self, start: Optional[str], end: Optional[str]) -> "Panel":
        """Restrict to trading days in [start, end] (both inclusive, like
        pandas .slice_locs as used at reference dataset.py:97-99)."""
        lo, hi = self.dates.slice_locs(
            start=pd.Timestamp(start) if start else None,
            end=pd.Timestamp(end) if end else None,
        )
        return Panel(
            values=self.values[:, lo:hi],
            valid=self.valid[lo:hi],
            dates=self.dates[lo:hi],
            instruments=self.instruments,
        )

    def locate(self, start: Optional[str], end: Optional[str]) -> tuple:
        """Day-index range [lo, hi) for a date range."""
        return self.dates.slice_locs(
            start=pd.Timestamp(start) if start else None,
            end=pd.Timestamp(end) if end else None,
        )


def load_frame(
    path: str,
    select_feature: Optional[Sequence[str]] = None,
    max_columns: int = 159,
) -> pd.DataFrame:
    """Read a reference-schema pickle and normalize its columns.

    Mirrors reference main.py:36-37: keep the first 159 columns (drop any
    market-info extras) and rename the last kept column to 'LABEL0'.
    """
    df = pd.read_pickle(path)
    if isinstance(df.columns, pd.MultiIndex):
        # qlib writes (col_set, name) MultiIndex columns; flatten to names.
        df.columns = [c[-1] for c in df.columns]
    df = df.iloc[:, :max_columns]
    df = df.rename(columns={df.columns[-1]: "LABEL0"})
    if select_feature is not None:
        df = df[list(select_feature) + ["LABEL0"]]
    return df


def build_panel(df: pd.DataFrame) -> Panel:
    """Densify a MultiIndex (datetime, instrument) frame to a Panel.

    Equivalent information to the reference's date x instrument index grid
    (dataset.py:127-137) but materialized as one dense float array rather
    than an object-dtype frame of row indices.
    """
    if list(df.index.names) != ["datetime", "instrument"]:
        raise ValueError(f"expected (datetime, instrument) index, got {df.index.names}")
    df = df.sort_index()
    dates = df.index.get_level_values(0).unique().sort_values()
    instruments = df.index.get_level_values(1).unique().sort_values()
    d, i, c = len(dates), len(instruments), df.shape[1]

    date_pos = pd.Series(np.arange(d), index=dates)
    inst_pos = pd.Series(np.arange(i), index=instruments)
    rows = date_pos.loc[df.index.get_level_values(0)].to_numpy()
    cols = inst_pos.loc[df.index.get_level_values(1)].to_numpy()

    from factorvae_tpu import native

    data = df.to_numpy(dtype=np.float32)
    values = native.scatter_panel(data, rows, cols, d, i)
    if values is None:
        values = np.full((i, d, c), np.nan, dtype=np.float32)
        values[cols, rows] = data
    valid = np.zeros((d, i), dtype=bool)
    valid[rows, cols] = True
    return Panel(
        values=values,
        valid=valid,
        dates=pd.DatetimeIndex(dates),
        instruments=np.asarray(instruments),
    )


def panel_to_frame(panel: Panel) -> pd.DataFrame:
    """Inverse of `build_panel` (drops absent rows); used by tests."""
    i, d, c = panel.values.shape
    mask = panel.valid.T.reshape(-1)  # (I*D,) instrument-major
    idx = pd.MultiIndex.from_product(
        [panel.dates, panel.instruments], names=["datetime", "instrument"]
    )
    # values is instrument-major; reorder to (D, I, C) date-major flat
    flat = np.swapaxes(panel.values, 0, 1).reshape(d * i, c)
    keep = panel.valid.reshape(-1)
    del mask
    return pd.DataFrame(flat[keep], index=idx[keep])
