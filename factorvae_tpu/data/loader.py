"""Residency-governed day-batch dataset.

Replaces the reference's TSDatasetH + DateGroupedBatchSampler + DataLoader
assembly (dataset.py:187-274). The semantic is identical — one batch =
one trading day's full cross-section, optionally day-shuffled
(dataset.py:227-234) — but the mechanics are TPU-first: under the default
"hbm" residency the whole panel sits in HBM as static-shape arrays, a
"batch" is just a day index, and the window gather runs inside the jitted
train step (windows.py). There are no worker processes, no host->device
copies per step, and no variable batch shapes. Under the "stream"
residency (plan.panel_residency; data/stream.py, docs/streaming.md) the
panel stays host-resident and epochs consume double-buffered prefetched
mini-panel chunks — bitwise-identical results with device residency
independent of history length.
"""

from __future__ import annotations

if __name__ == "__main__":
    # Plain-script invocation (`python factorvae_tpu/data/loader.py`):
    # bootstrap the repo root onto sys.path and force host-CPU devices so
    # the smoke entry below works in sandboxes whose TPU plugin pins
    # jax_platforms (see utils/testing.py) — must happen before the
    # package imports under this line.
    import os as _os
    import sys as _sys

    _sys.path.insert(
        0,
        _os.path.dirname(
            _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
        ),
    )
    from factorvae_tpu.utils.testing import force_host_devices as _fhd

    _fhd(1)

from typing import Iterator, Optional, Tuple

import numpy as np
import pandas as pd

import jax.numpy as jnp

from factorvae_tpu.data.panel import Panel
from factorvae_tpu.data.windows import compute_fill_maps, gather_day


def _round_up(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


class PanelDataset:
    """Panel + split bookkeeping, resident per the plan's residency knob.

    The cross-section is padded to ``n_max`` (a multiple of `pad_multiple`
    for MXU tiling / even 'stock'-axis sharding); padded instruments are
    permanently invalid.

    ``residency`` (plan.panel_residency) picks where the panel lives:

    - ``"hbm"`` (default, today's path kept bitwise): the whole
      (n_max, D, C+1) panel ships to the default device once and every
      jitted step gathers from it — zero per-step host traffic, but the
      panel must fit in device memory alongside activations.
    - ``"stream"``: the panel stays HOST-resident numpy; training/
      scoring consume host-gathered day-chunk batches double-buffered
      onto the device (data/stream.py), so device residency is
      O(2 chunks) regardless of history length D. Bitwise-equal results
      to ``"hbm"`` (tests/test_stream.py).
    """

    def __init__(
        self,
        panel: Panel,
        seq_len: int = 20,
        max_stocks: Optional[int] = None,
        pad_multiple: int = 8,
        residency: str = "hbm",
    ):
        if residency not in ("hbm", "stream"):
            raise ValueError(
                f"residency must be 'hbm' or 'stream'; got {residency!r}")
        self.panel = panel
        self.seq_len = seq_len
        self.residency = residency
        n_inst = panel.num_instruments
        n_max = max_stocks or _round_up(n_inst, pad_multiple)
        if n_max < n_inst:
            raise ValueError(f"max_stocks={n_max} < {n_inst} instruments")
        self.n_max = n_max
        # Padding accounting (bench.py masked-compute reporting): every
        # matmul runs n_max rows but only n_real carry data — the gap is
        # dead compute the scale-aware pad policy (plan.pad_target_policy)
        # exists to minimize.
        self.n_real = n_inst

        d = panel.num_days
        values = np.full((n_max, d, panel.values.shape[-1]), np.nan, np.float32)
        values[:n_inst] = panel.values
        valid = np.zeros((d, n_max), bool)
        valid[:, :n_inst] = panel.valid
        last_valid, next_valid = compute_fill_maps(valid)

        if residency == "hbm":
            # Ship to the default device once; everything downstream
            # indexes it.
            self.values = jnp.asarray(values)
            self.last_valid = jnp.asarray(last_valid)
            self.next_valid = jnp.asarray(next_valid)
        else:
            # Host-pinned residency: the device never holds the panel —
            # only the per-chunk batches the prefetcher ships.
            self.values_np = values
            self.last_valid_np = last_valid
            self.next_valid_np = next_valid
        self.valid = valid
        self.dates = panel.dates
        self.instruments = panel.instruments

    def __getattr__(self, name):
        if name in ("values", "last_valid", "next_valid"):
            raise AttributeError(
                f"PanelDataset.{name}: no device-resident panel under "
                "residency='stream' — this consumer needs the HBM path "
                "(rebuild the dataset with residency='hbm') or the "
                "streaming variant (data/stream.py)")
        if name in ("values_np", "last_valid_np", "next_valid_np"):
            raise AttributeError(
                f"PanelDataset.{name}: host panel copies are only kept "
                "under residency='stream' (the HBM path ships them to "
                "device and drops the host side)")
        raise AttributeError(name)

    @property
    def dead_compute_frac(self) -> float:
        """Fraction of cross-section rows that are permanent padding."""
        return 1.0 - self.n_real / self.n_max

    # ---- incremental append (walk-forward; data/append.py) ---------------

    def extend_days(self, piece: Panel) -> bool:
        """Append new trading days in place; returns True when days
        were added, False for the idempotent no-op (every incoming day
        already present — the resumed-cycle path).

        The walk-forward loop's serving-side pickup (ROADMAP item 2):
        under ``residency='stream'`` the panel is host numpy, so this
        is a concatenate + fill-map recompute — NO device transfer, no
        re-pickling, and the per-chunk batch shapes the scoring jits
        trace are unchanged (zero recompiles on append). Under ``hbm``
        the grown panel re-ships to the device once and the day axis
        D changes, so the whole-panel scoring jits retrace — stream
        residency is the serving mode the nightly loop wants.

        The instrument axis is fixed: incoming instruments must be a
        subset of this dataset's (aligned by data/append.py's rule).
        Fill maps are recomputed over the FULL valid matrix — bfill
        reaches forward, so trailing gaps before the append may now
        resolve to the new days, exactly as a fresh dataset built on
        the appended panel would resolve them (pinned bitwise in
        tests/test_wf.py). Callers sharing this dataset with a serving
        thread serialize through the daemon's tick lock
        (ScoringDaemon.extend_dataset)."""
        from factorvae_tpu.data.append import align_to_instruments

        piece = align_to_instruments(piece, self.instruments)
        if piece.dates[0] <= self.dates[-1]:
            if (piece.dates[-1] <= self.dates[-1]
                    and piece.dates.isin(self.dates).all()):
                return False
            raise ValueError(
                f"extend_days: incoming days start at "
                f"{piece.dates[0].date()} but the dataset already ends "
                f"at {self.dates[-1].date()}; appends must be strictly "
                f"newer (or fully present, for idempotent resume)")
        d_new = piece.num_days
        c = piece.values.shape[-1]
        add_vals = np.full((self.n_max, d_new, c), np.nan, np.float32)
        add_vals[: self.n_real] = piece.values
        add_valid = np.zeros((d_new, self.n_max), bool)
        add_valid[:, : self.n_real] = piece.valid
        if self.residency == "stream":
            values = np.concatenate([self.values_np, add_vals], axis=1)
        else:
            values = np.concatenate(
                [np.asarray(self.values), add_vals], axis=1)
        valid = np.concatenate([self.valid, add_valid], axis=0)
        last_valid, next_valid = compute_fill_maps(valid)
        if self.residency == "stream":
            self.values_np = values
            self.last_valid_np = last_valid
            self.next_valid_np = next_valid
        else:
            self.values = jnp.asarray(values)
            self.last_valid = jnp.asarray(last_valid)
            self.next_valid = jnp.asarray(next_valid)
        self.valid = valid
        self.dates = self.dates.append(piece.dates)
        # The wrapped Panel grows too: split_days/locate resolve date
        # ranges through it, and a rebuilt dataset must see the same
        # underlying history.
        self.panel = Panel(
            values=np.concatenate([self.panel.values, piece.values],
                                  axis=1),
            valid=np.concatenate([self.panel.valid, piece.valid],
                                 axis=0),
            dates=self.dates,
            instruments=self.panel.instruments,
        )
        return True

    # ---- splits ----------------------------------------------------------

    def split_days(self, start: Optional[str], end: Optional[str]) -> np.ndarray:
        """Day indices whose date lies in [start, end] — the analogue of the
        reference's slice_locs sample restriction (dataset.py:97-99). The
        look-back windows of early split days still reach into earlier
        days, exactly as in the reference (the sampler holds the full
        frame and only restricts sample positions)."""
        lo, hi = self.panel.locate(start, end)
        days = np.arange(lo, hi, dtype=np.int32)
        # Drop days with an empty cross-section (can happen on synthetic
        # panels; reference days always have rows).
        return days[self.valid[days].any(axis=1)]

    # ---- batching --------------------------------------------------------

    def day_batch(self, day) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        """(x, y, mask) for one day; usable eagerly or under jit (hbm).
        Stream datasets resolve it from the host panel — same values."""
        if self.residency == "stream":
            x, y, mask, _ = self.gather_batch_host(np.asarray([day]))
            return jnp.asarray(x[0]), jnp.asarray(y[0]), jnp.asarray(mask[0])
        return gather_day(
            self.values, self.last_valid, self.next_valid, day, self.seq_len
        )

    def gather_batch_host(self, days: np.ndarray):
        """(x, y, mask, day_w) for a day batch, gathered on HOST from the
        stream-resident panel (windows.gather_days_host; -1 = padding).
        Bitwise the device gather's batches."""
        from factorvae_tpu.data.windows import gather_days_host

        return gather_days_host(
            self.values_np, self.last_valid_np, self.next_valid_np,
            np.asarray(days, np.int32), self.seq_len)

    @property
    def panel_nbytes(self) -> int:
        """Bytes of the dense (n_max, D, C+1) panel — the HBM residency
        the stream path avoids (bench.py transfer accounting)."""
        arr = self.values_np if self.residency == "stream" else self.values
        return int(arr.size) * int(arr.dtype.itemsize)

    def day_labels(self, days: np.ndarray) -> np.ndarray:
        """(len(days), n_max) label column in day-major order, resolved
        from whichever residency holds the panel (one definition for the
        score-frame builders, eval/predict._frame_pieces)."""
        days = np.asarray(days, dtype=np.intp)
        if self.residency == "stream":
            return self.values_np[:, :, -1].T[days]
        return np.asarray(self.values[:, :, -1]).T[days]

    def iter_days(
        self, days: np.ndarray, shuffle: bool = False, seed: int = 0
    ) -> Iterator[int]:
        """Host-side day iterator (eval/debug path). Training uses the
        fully on-device epoch scan in train/loop.py instead."""
        order = np.array(days)
        if shuffle:
            np.random.default_rng(seed).shuffle(order)
        yield from order.tolist()

    def epoch_order(
        self, days: np.ndarray, shuffle: bool, seed: int, epoch: int, pad_to: int = 0
    ) -> np.ndarray:
        """Day order for one epoch, optionally padded (by repeating the
        final day with a zero-weight marker handled by the loop) so the
        epoch length is a multiple of `days_per_step * data_axis`."""
        order = np.array(days)
        if shuffle:
            np.random.default_rng((seed, epoch)).shuffle(order)
        if pad_to:
            rem = (-len(order)) % pad_to
            if rem:
                order = np.concatenate([order, np.full(rem, -1, order.dtype)])
        return order

    def index_frame(self, days: np.ndarray) -> pd.MultiIndex:
        """(datetime, instrument) MultiIndex of valid samples in day order —
        the analogue of TSDataSampler.get_index() (dataset.py:124-125),
        used to align exported scores."""
        days = np.asarray(days, dtype=np.intp)
        day_pos, inst_pos = np.nonzero(self.valid[days])
        return pd.MultiIndex.from_arrays(
            [self.dates[days[day_pos]], self.instruments[inst_pos]],  # graftlint: disable=JGL009 extend_days mutations are serialized by the daemon tick lock that also covers every serving-thread reader; index_frame's callers are main-line score exporters running between cycles, never concurrent with an append
            names=["datetime", "instrument"],
        )


if __name__ == "__main__":
    # Smoke entry mirroring the reference's only runnable "test"
    # (dataset.py:276-292): iterate a few day-batches and print shapes.
    import sys

    from factorvae_tpu.data.panel import build_panel, load_frame
    from factorvae_tpu.data.synthetic import synthetic_frame

    if len(sys.argv) > 1:
        frame = load_frame(sys.argv[1])
    else:
        frame = synthetic_frame(num_days=12, num_instruments=8, num_features=6)
    ds = PanelDataset(build_panel(frame), seq_len=5)
    days = ds.split_days(None, None)
    for d in list(days[:3]):
        x, y, mask = ds.day_batch(int(d))
        print(f"day {ds.dates[int(d)].date()}: x{tuple(x.shape)} "
              f"y{tuple(y.shape)} valid={int(mask.sum())}/{ds.n_max}")
    print("Done")
