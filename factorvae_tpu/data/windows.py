"""Look-back-window construction with ffill+bfill semantics.

Reference semantics being reproduced (dataset.py:139-151 `_get_indices`
with ``fillna_type='ffill+bfill'`` as wired at dataset.py:266): a sample
(day d, instrument i) is a `T`-row window over trading days
[d-T+1 .. d]; a day on which the instrument has no row — or a position
before the start of the calendar — takes the nearest *preceding* valid row
within the window, and leading gaps take the nearest *following* valid row
within the window. Only window-local rows are used for filling.

TPU-first re-design: the reference gathers rows per sample on host inside
DataLoader workers. Here two tiny int32 maps

    last_valid[d, i] = most recent day <= d with a row (-1 if none)
    next_valid[d, i] = earliest day  >= d with a row ( D if none)

are precomputed once on host (O(D*I)); the actual `(I, T, C)` window
gather happens **on device inside the jitted step** via
`take_along_axis`, so the full windowed tensor (which would be tens of GB
materialized) never exists — only the dense panel (~0.5 GB) lives in HBM.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp


def compute_fill_maps(valid: np.ndarray):
    """valid: (D, I) bool -> (last_valid, next_valid), both (D, I) int32.

    last_valid[d,i] is the largest d' <= d with valid[d',i] (-1 if none);
    next_valid[d,i] the smallest d' >= d (D if none).

    Uses the native C++ pass (factorvae_tpu/native) when available;
    numpy otherwise (identical outputs, tested against each other).
    """
    from factorvae_tpu import native

    nat = native.fill_maps(np.asarray(valid))
    if nat is not None:
        return nat
    d, i = valid.shape
    idx = np.arange(d, dtype=np.int32)[:, None]
    last_valid = np.maximum.accumulate(np.where(valid, idx, -1), axis=0)
    rev = valid[::-1]
    nv_rev = np.maximum.accumulate(np.where(rev, idx, -1), axis=0)
    next_valid = np.where(nv_rev[::-1] >= 0, d - 1 - nv_rev[::-1], d)
    return last_valid.astype(np.int32), next_valid.astype(np.int32)


def fill_indices_host(valid: np.ndarray, day: int, step_len: int) -> np.ndarray:
    """Host oracle: per-instrument day indices for day `day`'s window,
    (I, T) int32; -1 marks an unresolvable position (no valid row in the
    window — the reference would produce its all-NaN sentinel row there,
    dataset.py:81-84). Used by tests to pin the device gather's semantics.
    """
    d_total, n_inst = valid.shape
    t = step_len
    out = np.full((n_inst, t), -1, dtype=np.int32)
    for i in range(n_inst):
        pos = np.arange(day - t + 1, day + 1)
        vals = np.full(t, np.nan)
        for k, p in enumerate(pos):
            if 0 <= p < d_total and valid[p, i]:
                vals[k] = p
        # ffill then bfill (reference dataset.py:148 applied to index
        # positions, which carry whole rows)
        for k in range(1, t):
            if np.isnan(vals[k]):
                vals[k] = vals[k - 1]
        for k in range(t - 2, -1, -1):
            if np.isnan(vals[k]):
                vals[k] = vals[k + 1]
        out[i] = np.where(np.isnan(vals), -1, vals).astype(np.int32)
    return out


def window_fill_indices_np(
    last_valid: np.ndarray, next_valid: np.ndarray, day: int, step_len: int
) -> np.ndarray:
    """Host twin of `window_fill_indices`: identical index math in numpy,
    for the out-of-core stream path (data/stream.py) where the panel
    never leaves host memory. Pure integer selection — bitwise-equal
    fill maps by construction (pinned in tests/test_stream.py)."""
    d_total = last_valid.shape[0]
    t = step_len
    day = int(day)
    p = day - t + 1 + np.arange(t, dtype=np.int32)       # (T,) window days
    pc = np.clip(p, 0, d_total - 1)
    lv = last_valid[pc]                                   # (T, I)
    w_start = day - t + 1
    ff_ok = (p >= 0)[:, None] & (lv >= max(w_start, 0))
    fv = next_valid[min(max(w_start, 0), d_total - 1)]    # (I,)
    bf_ok = fv <= day
    fallback = np.where(bf_ok, fv, day)[None, :]
    fill = np.where(ff_ok, lv, fallback)                  # (T, I)
    return fill.T.astype(np.int32)                        # (I, T)


def gather_days_host(
    values: np.ndarray,
    last_valid: np.ndarray,
    next_valid: np.ndarray,
    days: np.ndarray,
    step_len: int,
):
    """Host twin of the device per-day gather vmapped over a day batch
    (train/loop.py `batch_for`): (x, y, mask, day_w) for `days` (B,)
    int32 with -1 epoch padding, gathered from the HOST-resident panel.

    Gather/select/NaN-fill only — no float arithmetic — so the batches
    are bitwise what the device gather produces from the same panel;
    the jitted consumers then run the identical model graph on them.
      x     (B, I, T, C)  float32, NaN-free
      y     (B, I)        float32 day labels (NaN where absent)
      mask  (B, I)        bool, instrument has a row AND day is real
      day_w (B,)          float32 1/0 real-day weights
    """
    days = np.asarray(days, np.int32)
    safe = np.maximum(days, 0)
    xs, ys, masks = [], [], []
    for d in safe:
        fill = window_fill_indices_np(last_valid, next_valid, int(d), step_len)
        window = np.take_along_axis(values, fill[:, :, None], axis=1)
        xs.append(np.nan_to_num(window[:, :, :-1]))
        ys.append(values[:, int(d), -1])
        masks.append(last_valid[int(d)] == int(d))
    x = np.stack(xs)
    y = np.stack(ys)
    mask = np.stack(masks) & (days >= 0)[:, None]
    day_w = (days >= 0).astype(np.float32)
    return x, y, mask, day_w


def chunk_mini_panel(
    values: np.ndarray,
    last_valid: np.ndarray,
    next_valid: np.ndarray,
    days: np.ndarray,
    step_len: int,
):
    """Relocatable mini-panel for a chunk of (possibly shuffled) days —
    the out-of-core stream path's transfer unit (data/stream.py).

    Returns ``(local_days, cvalues, clv, cnv)`` such that running the
    UNCHANGED device gather (`gather_day` / loop.batch_for) on the mini
    panel with `local_days` yields bitwise the batches the full panel
    yields for `days`. Each day gets its own T-row slab: day s (flat
    chunk position) lives at local days [s*T, (s+1)*T), its query day at
    s*T + T - 1, and the fill maps are REMAPPED so the device's
    ffill/bfill arithmetic resolves to the same original rows:

      clv[s*T + t] = s*T + (last_valid[clip(w+t)] - w)  where the HBM
                     path's ffill would accept that row, else -1
      cnv[s*T]     = s*T + (next_valid[clip(w, 0, D-1)] - w) where its
                     bfill would accept, else D' (out of range)

    with w = day - T + 1. Keeping the gather ON DEVICE (rather than
    shipping pre-gathered windows) matters: the chunked scan then traces
    the exact graph the whole-epoch scan traces, which is what keeps the
    stream residency bitwise-equal (see loop.train_chunk).

    Padded entries (day == -1) keep local day -1; their slab duplicates
    day 0's clipped window (the consumer zero-weights them exactly like
    the HBM path zero-weights its day-0 gather for pads).
    """
    days = np.asarray(days, np.int32)
    m = len(days)
    t = int(step_len)
    d_total = last_valid.shape[0]
    safe = np.maximum(days, 0).astype(np.int64)
    w_start = safe - t + 1                                   # (m,)
    p = w_start[:, None] + np.arange(t)                      # (m, T) unclipped
    pc = np.clip(p, 0, d_total - 1)
    cvalues = np.ascontiguousarray(values[:, pc.reshape(-1), :])
    base = (np.arange(m, dtype=np.int64) * t)[:, None]       # (m, 1)

    lv = last_valid[pc]                                      # (m, T, I)
    ff_ok = (p >= 0)[:, :, None] & (
        lv >= np.maximum(w_start, 0)[:, None, None])
    clv = np.where(
        ff_ok, base[:, :, None] + (lv - w_start[:, None, None]), -1
    ).reshape(m * t, -1).astype(np.int32)

    cnv = np.full((m * t, lv.shape[-1]), m * t, np.int32)
    fv = next_valid[np.clip(w_start, 0, d_total - 1)]        # (m, I)
    bf_ok = fv <= safe[:, None]
    cnv[np.arange(m) * t] = np.where(
        bf_ok, base + (fv - w_start[:, None]), m * t).astype(np.int32)

    local_days = np.where(
        days >= 0, np.arange(m, dtype=np.int32) * t + t - 1, -1
    ).astype(np.int32)
    return local_days, cvalues, clv, cnv


def window_fill_indices(
    last_valid: jnp.ndarray, next_valid: jnp.ndarray, day, step_len: int
) -> jnp.ndarray:
    """Device-side fill indices for one day: (I, T) int32.

    `day` may be a traced scalar. Positions with no valid row anywhere in
    the window resolve to `day` (clamped gather; such instruments are
    masked out of the batch anyway since valid[day, i] is False for them).
    """
    d_total = last_valid.shape[0]
    t = step_len
    p = day - t + 1 + jnp.arange(t, dtype=jnp.int32)     # (T,) window days
    pc = jnp.clip(p, 0, d_total - 1)
    lv = last_valid[pc]                                   # (T, I)
    w_start = day - t + 1
    # lv == -1 means "no valid row ever"; the clamp to 0 also keeps it from
    # passing the in-window check when w_start is negative (early days).
    ff_ok = (p >= 0)[:, None] & (lv >= jnp.maximum(w_start, 0))
    fv = next_valid[jnp.clip(w_start, 0, d_total - 1)]    # (I,)
    bf_ok = fv <= day
    fallback = jnp.where(bf_ok, fv, day)[None, :]
    fill = jnp.where(ff_ok, lv, fallback)                 # (T, I)
    return fill.T.astype(jnp.int32)                       # (I, T)


def gather_day(
    values: jnp.ndarray,
    last_valid: jnp.ndarray,
    next_valid: jnp.ndarray,
    day,
    step_len: int,
):
    """Gather one day's padded cross-section from the HBM-resident panel.

    values: (I, D, C+1). Returns (x, y, mask):
      x    (I, T, C)  features, NaN-free (padded/missing -> 0)
      y    (I,)       day-`day` labels (may be NaN on inference panels)
      mask (I,)       instrument has a row on `day`
    """
    fill = window_fill_indices(last_valid, next_valid, day, step_len)  # (I, T)
    window = jnp.take_along_axis(values, fill[:, :, None], axis=1)     # (I, T, C+1)
    x = jnp.nan_to_num(window[:, :, :-1], nan=0.0)
    y = values[:, day, -1]  # label = the day-d row's last column
    mask = last_valid[day] == day  # valid[day, i] <=> last_valid[day,i]==day
    return x, y, mask
