"""Out-of-core panel streaming: double-buffered host->device chunks.

The HBM residency path ships the whole (n_max, D, C+1) panel to the
device once (loader.py). This module is the `panel_residency="stream"`
counterpart: the panel stays host-resident numpy, the epoch is consumed
as day-chunk batches, and a single background worker produces chunk k+1
(host window gather + `jax.device_put`) while the jitted consumer runs
chunk k. Double buffering by construction: at most two chunks are alive
on device, so device residency is O(2 * chunk) regardless of history
length D.

The sanctioned transfer idiom is CHUNK-granularity: one `device_put` of
the whole gathered batch per chunk (graftlint JGL001 flags per-element
`device_put` pulls/pushes inside host loops; this loop is the corrected
shape it points to).

`ChunkStream` also keeps the transfer ledger bench.py reports:
`bytes_put` (host->device traffic), `produce_seconds` (gather + put
time on the worker), `wait_seconds` (consumer stalls on an unfinished
chunk). `overlap_frac = 1 - wait/produce` is the fraction of transfer
work hidden behind compute — ~1.0 when the pipeline fully overlaps,
~0.0 when every chunk is a synchronous stall. On hosts where producer
and consumer share the same cores (the CPU sandbox) there is no real
transfer gap to hide and the number is reported as-is, not a claim.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Iterator

import jax

from factorvae_tpu.chaos import fault as chaos_fault
from factorvae_tpu.utils.logging import timeline_event, timeline_span_at


def _tree_nbytes(tree: Any) -> int:
    return sum(
        int(getattr(leaf, "nbytes", 0)) for leaf in jax.tree.leaves(tree)
    )


def overlap_frac(wait_seconds: float, produce_seconds: float) -> float:
    """Fraction of produce (gather+put) time hidden behind consumer
    compute, clamped to [0, 1]; 0.0 when nothing was produced. ONE
    definition of the ledger's headline ratio — ChunkStream and the
    bench.py BENCH_STREAM payload both report exactly this."""
    if produce_seconds <= 0.0:
        return 0.0
    return max(0.0, min(1.0, 1.0 - wait_seconds / produce_seconds))


class ChunkStream:
    """Iterate device-resident chunk batches with one chunk of lookahead.

    ``make_chunk(i)`` builds the i-th HOST chunk (a numpy pytree; for
    epochs, the remapped mini-panel from windows.chunk_mini_panel). The
    worker thread runs ``device_put(make_chunk(i+1))`` while the
    consumer holds chunk i.
    Iteration is strictly in order — chunk order is the SGD step order,
    part of the bitwise contract with the HBM path.

    ``placement`` (optional) replaces the bare whole-tree
    ``jax.device_put`` with a target-sharding put — the mesh path
    (parallel.sharding.chunk_placement): each chunk lands pre-sharded
    per the panel partition rules, and on a multi-process mesh each
    host ships only its addressable slice of the slab.
    """

    #: produce-side failures retry this many extra times with bounded
    #: exponential backoff before surfacing — a transient host/transfer
    #: flake costs one retry, never the epoch. The retried gather+put is
    #: deterministic, so a retry is bitwise the first attempt.
    MAX_RETRIES = 2
    RETRY_BACKOFF_S = 0.05

    def __init__(self, make_chunk: Callable[[int], Any], n_chunks: int,
                 placement: Callable[[Any], Any] | None = None):
        self._make_chunk = make_chunk
        self._placement = placement or jax.device_put
        self.n_chunks = int(n_chunks)
        # Transfer-ledger counters are written by the PREFETCH WORKER
        # (produce side) and read by the consumer/bench (graftlint
        # JGL009: `x += 1` on a float is a read-modify-write that can
        # lose an update across threads); one uncontended lock per
        # CHUNK guards them — nothing on the per-step hot path.
        self._lock = threading.Lock()
        self.bytes_put = 0
        self.produce_seconds = 0.0
        self.wait_seconds = 0.0
        self.retries = 0

    def _produce(self, i: int):
        """Worker-side gather + device_put with bounded-backoff retry.
        The chaos hooks (factorvae_tpu/chaos: `stream_stall` injects
        latency, `stream_fail` a failure) are None checks when no plan
        is installed — the clean path is byte-identical to pre-chaos."""
        last = None
        for attempt in range(self.MAX_RETRIES + 1):
            try:
                stall = chaos_fault("stream_stall", chunk=i)
                if stall is not None:
                    time.sleep(stall.delay_s)
                if chaos_fault("stream_fail", chunk=i) is not None:
                    raise RuntimeError(
                        f"chaos: injected stream transfer failure "
                        f"(chunk {i})")
                return self._produce_once(i)
            except Exception as e:
                last = e
                if attempt == self.MAX_RETRIES:
                    raise
                with self._lock:
                    self.retries += 1
                timeline_event("stream_retry", cat="recovery",
                               resource="stream", chunk=i,
                               attempt=attempt + 1, error=str(e))
                time.sleep(self.RETRY_BACKOFF_S * (2 ** attempt))
        raise last  # unreachable; keeps control flow explicit

    def _produce_once(self, i: int):
        t0 = time.perf_counter()
        host = self._make_chunk(i)
        nbytes = _tree_nbytes(host)
        # ONE chunk-granularity transfer; async on accelerators, so the
        # copy itself also overlaps the worker's next gather.
        dev = self._placement(host)
        # Counted only AFTER the put succeeds: a failed attempt that the
        # bounded retry re-runs must not double-count the chunk in the
        # transfer ledger the stream bench reports.
        t1 = time.perf_counter()
        with self._lock:
            self.bytes_put += nbytes
            self.produce_seconds += t1 - t0
        # The ledger as timeline spans (no-op without an installed
        # timeline): each worker-side gather+put window on the "stream"
        # lane, so `obs.timeline` can show how much of it hid behind
        # the "device" lane — the run-level overlap_frac.
        timeline_span_at("chunk_produce", t0, t1, cat="stream",
                         resource="stream", chunk=i, bytes=nbytes)
        return dev

    def __iter__(self) -> Iterator[Any]:
        if self.n_chunks <= 0:
            return
        with ThreadPoolExecutor(max_workers=1) as ex:
            fut = ex.submit(self._produce, 0)
            for i in range(self.n_chunks):
                nxt = (ex.submit(self._produce, i + 1)
                       if i + 1 < self.n_chunks else None)
                t0 = time.perf_counter()
                batch = fut.result()
                t1 = time.perf_counter()
                with self._lock:
                    self.wait_seconds += t1 - t0
                timeline_span_at("chunk_wait", t0, t1, cat="stream",
                                 resource="stream_wait", chunk=i)
                yield batch
                fut = nxt

    @property
    def overlap_frac(self) -> float:
        with self._lock:
            return overlap_frac(self.wait_seconds, self.produce_seconds)


def chunk_slices(n_steps: int, steps_per_chunk: int) -> list:
    """[(start, stop)] covering range(n_steps) in order. The tail chunk
    is SHORTER, never padded: padding would add SGD steps (extra RNG
    advances + optimizer updates) and break the bitwise contract with
    the whole-epoch scan; the cost is one extra compiled scan length."""
    if steps_per_chunk <= 0:
        raise ValueError(f"steps_per_chunk must be >= 1; got {steps_per_chunk}")
    return [(s, min(s + steps_per_chunk, n_steps))
            for s in range(0, n_steps, steps_per_chunk)]


def stream_epoch_batches(dataset, order, steps_per_chunk: int,
                         placement=None) -> ChunkStream:
    """ChunkStream over an epoch's (n_steps, B) day order for a
    stream-resident dataset. Each chunk is
    ``(order_local (k, B), (cvalues, clv, cnv))`` — the chunk's slice of
    the step order remapped onto a relocatable mini-panel
    (windows.chunk_mini_panel), which the chunked epoch fns
    (train/loop.py train_chunk / eval_chunk) consume through the SAME
    device gather the HBM path runs. ``placement`` puts each chunk onto
    a mesh per the panel partition rules (see ChunkStream)."""
    import numpy as np

    from factorvae_tpu.data.windows import chunk_mini_panel

    order = np.asarray(order, np.int32)
    slices = chunk_slices(order.shape[0], steps_per_chunk)
    b = order.shape[1]

    def make_chunk(i: int):
        lo, hi = slices[i]
        days = order[lo:hi].reshape(-1)
        local_days, cvalues, clv, cnv = chunk_mini_panel(
            dataset.values_np, dataset.last_valid_np, dataset.next_valid_np,
            days, dataset.seq_len)
        return local_days.reshape(hi - lo, b), (cvalues, clv, cnv)

    return ChunkStream(make_chunk, len(slices), placement=placement)
