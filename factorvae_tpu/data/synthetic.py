"""Synthetic panels for tests and benchmarks.

Produces the same schema the reference consumes (MultiIndex
(datetime, instrument) frame, C feature columns + LABEL0) with
controllable missingness and a plantable linear signal so the
overfit-integration test (SURVEY.md §4) has something learnable.
"""

from __future__ import annotations

import numpy as np
import pandas as pd

from factorvae_tpu.data.panel import Panel, build_panel


def synthetic_frame(
    num_days: int = 30,
    num_instruments: int = 12,
    num_features: int = 16,
    missing_prob: float = 0.1,
    signal: float = 0.5,
    seed: int = 0,
    label_scale: float = 1.0,
) -> pd.DataFrame:
    """Reference-schema frame with random (day, instrument) dropout.

    `label_scale` scales LABEL0 (e.g. 0.02 for daily-return-like
    magnitudes in demos; tests keep the default unit scale).
    """
    rng = np.random.default_rng(seed)
    dates = pd.bdate_range("2020-01-01", periods=num_days)
    instruments = np.array([f"SH{600000 + k}" for k in range(num_instruments)])
    w = rng.normal(size=(num_features,)) / np.sqrt(num_features)

    rows, feats, labels = [], [], []
    for d in dates:
        for inst in instruments:
            if rng.random() < missing_prob:
                continue
            f = rng.normal(size=(num_features,)).astype(np.float32)
            y = label_scale * (
                signal * float(f @ w) + (1 - signal) * float(rng.normal())
            )
            rows.append((d, inst))
            feats.append(f)
            labels.append(y)
    idx = pd.MultiIndex.from_tuples(rows, names=["datetime", "instrument"])
    df = pd.DataFrame(
        np.asarray(feats), index=idx, columns=[f"F{i}" for i in range(num_features)]
    )
    df["LABEL0"] = np.asarray(labels, dtype=np.float32)
    return df


def synthetic_panel(**kw) -> Panel:
    return build_panel(synthetic_frame(**kw))


def continuation_panel(
    instruments: np.ndarray,
    last_date,
    num_days: int,
    num_features: int,
    signal: float = 0.3,
    seed: int = 0,
) -> Panel:
    """Dense synthetic days CONTINUING an existing panel: same
    instrument axis, trading days strictly after `last_date`, features
    and planted-signal label drawn like `synthetic_panel_dense` but
    from `seed` alone — two calls with the same arguments produce
    bitwise-identical days (the determinism the walk-forward loop's
    idempotent append resume rests on, factorvae_tpu/wf)."""
    rng = np.random.default_rng(seed)
    instruments = np.asarray(instruments)
    n = len(instruments)
    dates = pd.bdate_range(
        pd.Timestamp(last_date) + pd.tseries.offsets.BDay(1),
        periods=num_days)
    feats = rng.normal(size=(n, num_days, num_features)).astype(np.float32)
    w = (rng.normal(size=(num_features,)) / np.sqrt(num_features)).astype(
        np.float32)
    label = signal * feats @ w + (1 - signal) * rng.normal(
        size=(n, num_days)).astype(np.float32)
    values = np.concatenate([feats, label[..., None]], axis=-1)
    return Panel(
        values=values,
        valid=np.ones((num_days, n), bool),
        dates=dates,
        instruments=instruments,
    )


def synthetic_panel_dense(
    num_days: int,
    num_instruments: int,
    num_features: int,
    signal: float = 0.3,
    seed: int = 0,
) -> Panel:
    """Fast array-native Panel (no pandas row loop) for benchmarks: full
    cross-section every day, features ~ N(0,1), label = planted linear
    signal + noise."""
    rng = np.random.default_rng(seed)
    feats = rng.normal(size=(num_instruments, num_days, num_features)).astype(np.float32)
    w = (rng.normal(size=(num_features,)) / np.sqrt(num_features)).astype(np.float32)
    label = signal * feats @ w + (1 - signal) * rng.normal(
        size=(num_instruments, num_days)
    ).astype(np.float32)
    values = np.concatenate([feats, label[..., None]], axis=-1)
    return Panel(
        values=values,
        valid=np.ones((num_days, num_instruments), bool),
        dates=pd.bdate_range("2015-01-01", periods=num_days),
        instruments=np.array([f"SH{600000 + k}" for k in range(num_instruments)]),
    )
