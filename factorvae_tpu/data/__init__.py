from factorvae_tpu.data.loader import PanelDataset
from factorvae_tpu.data.panel import Panel, build_panel, load_frame, panel_to_frame
from factorvae_tpu.data.synthetic import (
    synthetic_frame,
    synthetic_panel,
    synthetic_panel_dense,
)
from factorvae_tpu.data.windows import (
    compute_fill_maps,
    fill_indices_host,
    gather_day,
    window_fill_indices,
)

__all__ = [
    "Panel",
    "PanelDataset",
    "build_panel",
    "compute_fill_maps",
    "fill_indices_host",
    "gather_day",
    "load_frame",
    "panel_to_frame",
    "synthetic_frame",
    "synthetic_panel",
    "synthetic_panel_dense",
    "window_fill_indices",
]
