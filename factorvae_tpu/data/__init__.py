from factorvae_tpu.data.append import AppendError, PanelStore
from factorvae_tpu.data.loader import PanelDataset
from factorvae_tpu.data.panel import Panel, build_panel, load_frame, panel_to_frame
from factorvae_tpu.data.stream import ChunkStream, chunk_slices, stream_epoch_batches
from factorvae_tpu.data.synthetic import (
    continuation_panel,
    synthetic_frame,
    synthetic_panel,
    synthetic_panel_dense,
)
from factorvae_tpu.data.windows import (
    compute_fill_maps,
    fill_indices_host,
    gather_day,
    gather_days_host,
    window_fill_indices,
    window_fill_indices_np,
)

__all__ = [
    "AppendError",
    "ChunkStream",
    "Panel",
    "PanelDataset",
    "PanelStore",
    "build_panel",
    "chunk_slices",
    "compute_fill_maps",
    "continuation_panel",
    "fill_indices_host",
    "gather_day",
    "gather_days_host",
    "load_frame",
    "panel_to_frame",
    "stream_epoch_batches",
    "synthetic_frame",
    "synthetic_panel",
    "synthetic_panel_dense",
    "window_fill_indices",
    "window_fill_indices_np",
]
