"""Masked reductions over padded cross-sections.

The central TPU design decision (SURVEY.md §7.1): the reference feeds
variable-size per-day batches (N ~= 300 stocks, varying day to day;
reference dataset.py:207-238). XLA wants static shapes, so every day is
padded to ``N_max`` with a boolean validity mask, and every cross-stock
reduction in the model — the two softmaxes over the stock axis
(reference module.py:38,57,146), the portfolio matmul (module.py:64) and
the loss means (module.py:261) — becomes a masked reduction defined here.

On a day with no padding and an all-true mask each op is exactly its
unmasked counterpart, which is what the parity tests assert.
"""

from __future__ import annotations

import jax.numpy as jnp

_NEG_INF = -1e30


def masked_softmax(x: jnp.ndarray, mask: jnp.ndarray, axis: int = -1) -> jnp.ndarray:
    """Softmax over `axis` restricted to positions where `mask` is True.

    Padded positions get probability exactly 0 and never receive gradient
    mass. If a slice is fully masked the output is all zeros (not NaN).
    """
    mask = jnp.broadcast_to(mask, x.shape)
    x = jnp.where(mask, x, _NEG_INF)
    x = x - jnp.max(x, axis=axis, keepdims=True)  # stable; fully-masked -> 0
    ex = jnp.where(mask, jnp.exp(x), 0.0)
    denom = jnp.sum(ex, axis=axis, keepdims=True)
    return jnp.where(denom > 0, ex / jnp.where(denom > 0, denom, 1.0), 0.0)


def masked_mean(x: jnp.ndarray, mask: jnp.ndarray, axis=None) -> jnp.ndarray:
    """Mean of `x` over valid positions; 0 if nothing is valid."""
    mask = jnp.broadcast_to(mask, x.shape)
    total = jnp.sum(jnp.where(mask, x, 0.0), axis=axis)
    count = jnp.sum(mask, axis=axis)
    return jnp.where(count > 0, total / jnp.maximum(count, 1), 0.0)


def masked_mse(pred: jnp.ndarray, target: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Masked mean-squared error.

    With an all-true mask this equals ``F.mse_loss`` as used by the
    reference on its one reparameterized sample (module.py:261).
    """
    return masked_mean((pred - target) ** 2, mask)


def masked_gaussian_nll(
    mu: jnp.ndarray,
    sigma: jnp.ndarray,
    target: jnp.ndarray,
    mask: jnp.ndarray,
    eps: float = 1e-12,
) -> jnp.ndarray:
    """Masked mean Gaussian negative log-likelihood.

    The paper's reconstruction term (the reference approximates it with a
    single-sample MSE; BASELINE.json's north star asks for the analytic
    NLL — both are provided, selected by ``ModelConfig.recon_loss``).
    """
    var = sigma**2 + eps
    nll = 0.5 * (jnp.log(2.0 * jnp.pi * var) + (target - mu) ** 2 / var)
    return masked_mean(nll, mask)
