"""Closed-form Gaussian KL divergence.

Matches the reference's static ``FactorVAE.KL_Divergence`` exactly
(module.py:242-248):

    KL = sum_K [ log(sigma2/sigma1) + (sigma1^2 + (mu1-mu2)^2) / (2 sigma2^2) - 1/2 ]

i.e. KL(N(mu1,sigma1) || N(mu2,sigma2)) summed over the factor axis. Note
the reference *sums* over K while the reconstruction loss is a *mean* over
stocks — the scale imbalance is faithful-to-reference (SURVEY.md §3.2).
"""

from __future__ import annotations

import jax.numpy as jnp


def gaussian_kl(
    mu1: jnp.ndarray, sigma1: jnp.ndarray, mu2: jnp.ndarray, sigma2: jnp.ndarray
) -> jnp.ndarray:
    """Elementwise KL(N(mu1, sigma1) || N(mu2, sigma2))."""
    return (
        jnp.log(sigma2 / sigma1)
        + (sigma1**2 + (mu1 - mu2) ** 2) / (2.0 * sigma2**2)
        - 0.5
    )


def gaussian_kl_sum(
    mu1: jnp.ndarray,
    sigma1: jnp.ndarray,
    mu2: jnp.ndarray,
    sigma2: jnp.ndarray,
    guard: float = 1e-6,
) -> jnp.ndarray:
    """KL summed over all elements, with the reference's zero-sigma guard on
    the *second* (prior) distribution (module.py:264-265). The in-place
    masked write of the reference becomes a `where` (gradient-equivalent for
    sigma2 != 0; documented deviation for the measure-zero sigma2 == 0 case).
    """
    sigma2 = jnp.where(sigma2 == 0.0, guard, sigma2)
    return jnp.sum(gaussian_kl(mu1, sigma1, mu2, sigma2))
