"""Version-tolerant pallas-TPU names shared by the kernel modules.

`CompilerParams` is the jax>=0.7 name; older releases (the sandbox's
0.4.x included) call it `TPUCompilerParams`. Same fields either way.
Kept in one place so the next jax rename is a one-line fix (the
shard_map analogue lives in factorvae_tpu/parallel/compat.py).
"""

from __future__ import annotations

from jax.experimental.pallas import tpu as pltpu

CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams")
