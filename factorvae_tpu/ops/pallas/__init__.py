"""Pallas TPU kernels.

`attention.py` is NOT a legacy module: it is the forward kernel of the
shipped differentiable path — `attention_grad.fused_attention`'s
custom-vjp primal calls `multihead_cross_section_attention` directly
(attention_grad.py:157), so every model forward that selects the Pallas
attention runs it. `attention_grad` adds the flash-style recompute
backward around it.
"""

from factorvae_tpu.ops.pallas.attention import multihead_cross_section_attention

__all__ = ["multihead_cross_section_attention"]
