from factorvae_tpu.ops.pallas.attention import multihead_cross_section_attention

__all__ = ["multihead_cross_section_attention"]
