"""Measured per-shape kernel selection — thin shim over the planner.

The envelope predicates behind ``ModelConfig.use_pallas_* = "auto"``
(round-2 v5e race, RACE_KERNELS.json; PERF.md "Pallas kernels vs XLA on
the chip") moved to `factorvae_tpu.plan`, which generalizes the same
measured-envelope idea to the full execution plan (layout, day
batching, dtype, padding). This module keeps the historical import path
and the patchable `_on_tpu` seam the kernel tests use; the truth lives
in plan.py — update envelopes there.
"""

from __future__ import annotations

from factorvae_tpu import plan as _plan
from factorvae_tpu.plan import resolve  # noqa: F401  (re-export)

# Re-exported so existing callers/tests can read the measured envelope.
_GRU_RACED_N_MAX = _plan._GRU_RACED_N_MAX
_ATTN_RACED_N_MAX = _plan._ATTN_RACED_N_MAX


def _on_tpu() -> bool:
    """Patch point (tests mock this module's copy)."""
    return _plan._on_tpu()


def pallas_attention_wins(n: int, h: int, k: int) -> bool:
    """True where the fused attention beat XLA in the round-2 race;
    False outside the raced envelope (no extrapolated wins)."""
    return _plan.pallas_attention_wins(n, h, k, on_tpu=_on_tpu())


def pallas_gru_wins(n: int, t: int, h: int) -> bool:
    """True where the fused GRU recurrence beat XLA in the race;
    False outside the raced envelope (no extrapolated wins)."""
    return _plan.pallas_gru_wins(n, t, h, on_tpu=_on_tpu())
