"""Measured per-shape kernel selection — thin shim over the planner.

The envelope predicates behind ``ModelConfig.use_pallas_* = "auto"``
live in `factorvae_tpu.plan`, and since PR 19 they resolve in two
tiers: a plan row's raced ``kernels`` block (written by
``scripts/autotune_plan.py --kernels`` — a fresh pallas-vs-XLA race on
THIS rig, per op, fwd+bwd) wins when present; the round-2 v5e static
envelope (RACE_KERNELS.json chip records; PERF.md "Pallas kernels vs
XLA on the chip") is only the no-row fallback. See ``docs/kernels.md``
for the refresh workflow.

This module keeps the historical import path and the patchable
`_on_tpu` seam the kernel tests use. The wrappers below intentionally
expose only the fallback tier (no plan-row verdict argument): callers
that have a plan row go through `plan.plan_for(...)` /
`Plan.kernel_*`, not this shim. The truth lives in plan.py — update
envelopes there.
"""

from __future__ import annotations

from factorvae_tpu import plan as _plan
from factorvae_tpu.plan import resolve  # noqa: F401  (re-export)

# Re-exported so existing callers/tests can read the measured envelope.
_GRU_RACED_N_MAX = _plan._GRU_RACED_N_MAX
_ATTN_RACED_N_MAX = _plan._ATTN_RACED_N_MAX


def _on_tpu() -> bool:
    """Patch point (tests mock this module's copy)."""
    return _plan._on_tpu()


def pallas_attention_wins(n: int, h: int, k: int) -> bool:
    """True where the fused attention beat XLA in the round-2 race;
    False outside the raced envelope (no extrapolated wins). Fallback
    tier only — a plan row's raced verdict overrides via
    `Plan.kernel_attention`."""
    return _plan.pallas_attention_wins(n, h, k, on_tpu=_on_tpu())


def pallas_gru_wins(n: int, t: int, h: int) -> bool:
    """True where the fused GRU recurrence beat XLA in the race;
    False outside the raced envelope (no extrapolated wins). Fallback
    tier only — a plan row's raced verdict overrides via
    `Plan.kernel_gru`."""
    return _plan.pallas_gru_wins(n, t, h, on_tpu=_on_tpu())
