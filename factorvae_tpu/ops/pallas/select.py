"""Measured per-shape kernel selection (ModelConfig.use_pallas_* = "auto").

The round-2 race on a real v5e (scripts/race_kernels.py →
RACE_KERNELS.json; PERF.md "Pallas kernels vs XLA on the chip") showed
both paths are launch-bound at FactorVAE's op sizes, with reproducible
per-shape winners on the full fwd+bwd:

- attention: the fused kernel wins at small H (H=20: 1.38×/1.14×),
  ties at H>=48, and loses slightly at flagship K=96/H=64 backward.
- GRU: the fused recurrence wins at wide-N small-H short-T
  (N=1024/T=20/H=20: 1.38×), ties at H=64, and clearly loses at T=60
  (the VMEM-bounded 24-row backward blocking costs 1.6×).

"auto" applies those measurements. Shapes are static under jit, so the
choice is made at trace time with zero runtime cost. Off-TPU backends
resolve to the XLA path (the kernels would only run interpreted).
"""

from __future__ import annotations

import jax


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def pallas_attention_wins(n: int, h: int, k: int) -> bool:
    """True where the fused attention beat XLA in the round-2 race."""
    return _on_tpu() and h <= 24


def pallas_gru_wins(n: int, t: int, h: int) -> bool:
    """True where the fused GRU recurrence beat XLA in the race."""
    return _on_tpu() and n >= 512 and h <= 24 and t <= 20


def resolve(flag, measured: bool) -> bool:
    """Resolve a config tri-state (False | True | 'auto'). Any other
    string is an error — a truthy fallback would force the kernels on
    for a typo like "off" or "Auto"."""
    if isinstance(flag, str):
        if flag == "auto":
            return measured
        raise ValueError(
            f"use_pallas_* must be False, True or 'auto'; got {flag!r}")
    return bool(flag)
