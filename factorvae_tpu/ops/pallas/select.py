"""Measured per-shape kernel selection (ModelConfig.use_pallas_* = "auto").

The round-2 race on a real v5e (scripts/race_kernels.py →
RACE_KERNELS.json; PERF.md "Pallas kernels vs XLA on the chip") showed
both paths are launch-bound at FactorVAE's op sizes, with reproducible
per-shape winners on the full fwd+bwd:

- attention: the fused kernel wins at small H (H=20: 1.38×/1.14×),
  ties at H>=48, and loses slightly at flagship K=96/H=64 backward.
- GRU: the fused recurrence wins at wide-N small-H short-T
  (N=1024/T=20/H=20: 1.38×), ties at H=64, and clearly loses at T=60
  (the VMEM-bounded 24-row backward blocking costs 1.6×).

"auto" applies those measurements INSIDE the measured envelope only
(VERDICT r3 missing-#4: the round-2 grid raced N ∈ {360, 1024}; the r3
cross-day flattening moved the GRU's production row count to
N = B·N_pad = 2880 at flagship, a shape with no race row). Outside the
envelope auto resolves to the XLA path — extrapolating a win boundary
to 2.8× the largest raced N would turn an unmeasured kernel on in the
hot loop. When `scripts/race_kernels.py` (whose grid includes N=2880)
lands chip rows for the flattened shapes, widen `_GRU_RACED_N_MAX` /
`_ATTN_RACED_N_MAX` to the new measured envelope and encode any new
winners here.

Shapes are static under jit, so the choice is made at trace time with
zero runtime cost. Off-TPU backends resolve to the XLA path (the
kernels would only run interpreted).
"""

from __future__ import annotations

import jax

# Largest N with a measured race row (RACE_KERNELS.json, round-2 v5e).
_GRU_RACED_N_MAX = 1024
_ATTN_RACED_N_MAX = 1024


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def pallas_attention_wins(n: int, h: int, k: int) -> bool:
    """True where the fused attention beat XLA in the round-2 race;
    False outside the raced envelope (no extrapolated wins). The raced
    N values are {360, 1024} — both bounds are measured points."""
    return _on_tpu() and 360 <= n <= _ATTN_RACED_N_MAX and h <= 24


def pallas_gru_wins(n: int, t: int, h: int) -> bool:
    """True where the fused GRU recurrence beat XLA in the race;
    False outside the raced envelope (no extrapolated wins)."""
    return (_on_tpu() and 512 <= n <= _GRU_RACED_N_MAX
            and h <= 24 and t <= 20)


def resolve(flag, measured: bool) -> bool:
    """Resolve a config tri-state (False | True | 'auto'). Any other
    string is an error — a truthy fallback would force the kernels on
    for a typo like "off" or "Auto"."""
    if isinstance(flag, str):
        if flag == "auto":
            return measured
        raise ValueError(
            f"use_pallas_* must be False, True or 'auto'; got {flag!r}")
    return bool(flag)
