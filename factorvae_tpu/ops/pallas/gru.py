"""Fused GRU recurrence as a Pallas TPU kernel (forward + custom VJP).

The extractor's GRU runs as `lax.scan` over T (models/layers.py) — already
good under XLA. This kernel fuses the *whole recurrence* into one Pallas
call: the precomputed input projections, the hidden weights and the
running hidden state all stay in VMEM for all T steps, so nothing
round-trips HBM between timesteps. The input-side projection (one big
matmul) deliberately stays OUTSIDE the kernel where the MXU already
handles it optimally.

Mosaic-compatibility notes (the round-1 kernel compiled only in
interpret mode; VERDICT r1 item 3):
- The Pallas TPU lowering has no `dynamic_slice` on *values*, so the
  per-timestep read is a dynamic **ref** load (`ref[pl.ds(t, 1)]`) on a
  time-LEADING layout — dynamic indexing is only cheap/legal on leading
  dims.
- Gate projections arrive pre-split per gate (r/z/n) instead of one
  (N, T, 3H) block, so the kernel never slices the minor (lane) axis at
  non-128-aligned offsets.
- The backward's recomputed hidden sequence lives in a VMEM scratch ref
  (dynamic stores on values are likewise unsupported).

Backward is recompute-BPTT: re-run the recurrence storing the
(T+1, Nb, H) hidden sequence in scratch, then walk t = T-1..0
accumulating d_x*, d_Wh*, d_b* and the carried d_h.

Rows (stocks) are independent in the recurrence, so both kernels tile
the N axis into row blocks per grid step, sized by `_block_setup` from
the backward's MEASURED VMEM footprint (see its docstring) — 64 rows at
T=20, 24 rows at T=60/H=64. d_Wh/d_b accumulate across the sequential
grid.

Gate math matches layers.GRU exactly (torch layout [r | z | n]):

    r = sigmoid(x_r + h Wh_r + b_r)    z = sigmoid(x_z + h Wh_z + b_z)
    n = tanh(x_n + r * (h Wh_n + b_n))
    h' = (1 - z) * n + z * h

Selected via ``ModelConfig.use_pallas_gru``; interpret-mode off-TPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_N_BLOCK = 64        # max rows per grid step
_VMEM_BUDGET = 12 * 2 ** 20   # target bytes for the backward's refs
# (the v5e scoped-vmem limit is 16 MB; leave headroom for the compiler)


def _load_t(ref, t):
    """(T, Nb, H) ref -> (Nb, H) timestep t (dynamic leading-dim load)."""
    return ref[pl.ds(t, 1), :, :][0]


def _fwd_kernel(xr_ref, xz_ref, xn_ref, whr_ref, whz_ref, whn_ref,
                br_ref, bz_ref, bn_ref, hlast_ref):
    t_len, nb, h_dim = xr_ref.shape
    whr, whz, whn = whr_ref[:], whz_ref[:], whn_ref[:]
    br, bz, bn = br_ref[0, :], bz_ref[0, :], bn_ref[0, :]

    def step(t, h):
        ghr = jnp.dot(h, whr, preferred_element_type=jnp.float32) + br
        ghz = jnp.dot(h, whz, preferred_element_type=jnp.float32) + bz
        ghn = jnp.dot(h, whn, preferred_element_type=jnp.float32) + bn
        r = jax.nn.sigmoid(_load_t(xr_ref, t) + ghr)
        z = jax.nn.sigmoid(_load_t(xz_ref, t) + ghz)
        n = jnp.tanh(_load_t(xn_ref, t) + r * ghn)
        return (1.0 - z) * n + z * h

    h0 = jnp.zeros((nb, h_dim), jnp.float32)
    hlast_ref[:] = jax.lax.fori_loop(0, t_len, step, h0)


def _bwd_kernel(xr_ref, xz_ref, xn_ref, whr_ref, whz_ref, whn_ref,
                br_ref, bz_ref, bn_ref, dh_ref,
                dxr_ref, dxz_ref, dxn_ref,
                dwhr_ref, dwhz_ref, dwhn_ref,
                dbr_ref, dbz_ref, dbn_ref,
                hseq_ref):
    t_len, nb, h_dim = xr_ref.shape
    whr, whz, whn = whr_ref[:], whz_ref[:], whn_ref[:]
    br, bz, bn = br_ref[0, :], bz_ref[0, :], bn_ref[0, :]

    # recompute the hidden sequence into scratch: hseq[t] = h BEFORE step t
    hseq_ref[0] = jnp.zeros((nb, h_dim), jnp.float32)

    def fstep(t, _):
        h = _load_t(hseq_ref, t)
        ghr = jnp.dot(h, whr, preferred_element_type=jnp.float32) + br
        ghz = jnp.dot(h, whz, preferred_element_type=jnp.float32) + bz
        ghn = jnp.dot(h, whn, preferred_element_type=jnp.float32) + bn
        r = jax.nn.sigmoid(_load_t(xr_ref, t) + ghr)
        z = jax.nn.sigmoid(_load_t(xz_ref, t) + ghz)
        n = jnp.tanh(_load_t(xn_ref, t) + r * ghn)
        h_new = (1.0 - z) * n + z * h
        hseq_ref[pl.ds(t + 1, 1), :, :] = h_new[None]
        return 0

    jax.lax.fori_loop(0, t_len, fstep, 0)

    def bstep(i, carry):
        dh, dwhr, dwhz, dwhn, dbr, dbz, dbn = carry
        t = t_len - 1 - i
        h_prev = _load_t(hseq_ref, t)
        ghr = jnp.dot(h_prev, whr, preferred_element_type=jnp.float32) + br
        ghz = jnp.dot(h_prev, whz, preferred_element_type=jnp.float32) + bz
        ghn = jnp.dot(h_prev, whn, preferred_element_type=jnp.float32) + bn
        r = jax.nn.sigmoid(_load_t(xr_ref, t) + ghr)
        z = jax.nn.sigmoid(_load_t(xz_ref, t) + ghz)
        n = jnp.tanh(_load_t(xn_ref, t) + r * ghn)
        # h' = (1-z) n + z h_prev
        dz = dh * (h_prev - n)
        dn = dh * (1.0 - z)
        dh_prev = dh * z
        dtanh = dn * (1.0 - n * n)               # d(x_n + r*ghn)
        dr = dtanh * ghn
        dghn = dtanh * r
        dghr = dr * r * (1.0 - r)                # d(x_r + ghr)
        dghz = dz * z * (1.0 - z)                # d(x_z + ghz)
        dxr_ref[pl.ds(t, 1), :, :] = dghr[None]
        dxz_ref[pl.ds(t, 1), :, :] = dghz[None]
        dxn_ref[pl.ds(t, 1), :, :] = dtanh[None]
        dh_prev = dh_prev + (
            jnp.dot(dghr, whr.T, preferred_element_type=jnp.float32)
            + jnp.dot(dghz, whz.T, preferred_element_type=jnp.float32)
            + jnp.dot(dghn, whn.T, preferred_element_type=jnp.float32)
        )
        dwhr = dwhr + jnp.dot(h_prev.T, dghr,
                              preferred_element_type=jnp.float32)
        dwhz = dwhz + jnp.dot(h_prev.T, dghz,
                              preferred_element_type=jnp.float32)
        dwhn = dwhn + jnp.dot(h_prev.T, dghn,
                              preferred_element_type=jnp.float32)
        dbr = dbr + jnp.sum(dghr, axis=0, keepdims=True)
        dbz = dbz + jnp.sum(dghz, axis=0, keepdims=True)
        dbn = dbn + jnp.sum(dghn, axis=0, keepdims=True)
        return dh_prev, dwhr, dwhz, dwhn, dbr, dbz, dbn

    zero_w = jnp.zeros((h_dim, h_dim), jnp.float32)
    zero_b = jnp.zeros((1, h_dim), jnp.float32)
    init = (dh_ref[:], zero_w, zero_w, zero_w, zero_b, zero_b, zero_b)
    _, dwhr, dwhz, dwhn, dbr, dbz, dbn = jax.lax.fori_loop(
        0, t_len, bstep, init)

    # dWh/db accumulate across the sequential grid of row blocks
    @pl.when(pl.program_id(0) == 0)
    def _init():
        dwhr_ref[:] = jnp.zeros_like(dwhr_ref)
        dwhz_ref[:] = jnp.zeros_like(dwhz_ref)
        dwhn_ref[:] = jnp.zeros_like(dwhn_ref)
        dbr_ref[:] = jnp.zeros_like(dbr_ref)
        dbz_ref[:] = jnp.zeros_like(dbz_ref)
        dbn_ref[:] = jnp.zeros_like(dbn_ref)

    dwhr_ref[:] += dwhr
    dwhz_ref[:] += dwhz
    dwhn_ref[:] += dwhn
    dbr_ref[:] += dbr
    dbz_ref[:] += dbz
    dbn_ref[:] += dbn


def _split_gates(xi: jnp.ndarray, w_h: jnp.ndarray, b_h: jnp.ndarray,
                 n_pad: int):
    """(N, T, 3H) -> three time-leading (T, N+pad, H) gate streams plus
    per-gate weights/biases (torch layout [r | z | n])."""
    h_dim = w_h.shape[0]
    xs, ws, bs = [], [], []
    for g in range(3):
        x = xi[:, :, g * h_dim:(g + 1) * h_dim].astype(jnp.float32)
        x = jnp.transpose(x, (1, 0, 2))              # (T, N, H)
        if n_pad:
            x = jnp.pad(x, ((0, 0), (0, n_pad), (0, 0)))
        xs.append(x)
        ws.append(w_h[:, g * h_dim:(g + 1) * h_dim].astype(jnp.float32))
        bs.append(b_h[g * h_dim:(g + 1) * h_dim].reshape(1, -1)
                  .astype(jnp.float32))
    return xs, ws, bs


def _block_setup(n_rows: int, t_len: int, h_dim: int):
    """Row-block size bounded by the BACKWARD's measured VMEM footprint.

    The analytic model — six (T, Nb, H) refs double-buffered plus the
    (T+1, Nb, H) scratch, (13*T + 1) * H * 4 bytes/row — under-counts
    Mosaic's actual scoped allocation by ~2x (measured r2 on v5e at
    T=60/H=64: nb=64 allocated 24.41 MB and nb=48 18.30 MB against a
    16 MB limit, i.e. ~0.38 MB/row vs the model's 0.20 MB/row), so the
    sizing applies that empirical factor. Yields nb=64 at T=20/H<=64
    and nb=24 at T=60/H=64 (~9.2 MB measured-scale)."""
    per_row = 2 * (13 * t_len + 1) * h_dim * 4
    nb = max(8, min(_N_BLOCK, (_VMEM_BUDGET // per_row) // 8 * 8))
    nb = min(nb, n_rows) if n_rows >= 8 else n_rows
    n_pad = (-n_rows) % nb
    grid = ((n_rows + n_pad) // nb,)
    return nb, n_pad, grid


def _specs(t_len: int, nb: int, h_dim: int):
    x_spec = pl.BlockSpec((t_len, nb, h_dim), lambda i: (0, i, 0),
                          memory_space=pltpu.VMEM)
    w_spec = pl.BlockSpec((h_dim, h_dim), lambda i: (0, 0),
                          memory_space=pltpu.VMEM)
    b_spec = pl.BlockSpec((1, h_dim), lambda i: (0, 0),
                          memory_space=pltpu.VMEM)
    return x_spec, w_spec, b_spec


def _forward_impl(xs, ws, bs, n_rows, t_len, h_dim, nb, n_pad, grid):
    interpret = jax.default_backend() != "tpu"
    x_spec, w_spec, b_spec = _specs(t_len, nb, h_dim)
    out = pl.pallas_call(
        _fwd_kernel,
        grid=grid,
        in_specs=[x_spec] * 3 + [w_spec] * 3 + [b_spec] * 3,
        out_specs=pl.BlockSpec((nb, h_dim), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_rows + n_pad, h_dim), jnp.float32),
        interpret=interpret,
    )(*xs, *ws, *bs)
    return out[:n_rows]


@jax.custom_vjp
def gru_scan(xi: jnp.ndarray, w_h: jnp.ndarray, b_h: jnp.ndarray) -> jnp.ndarray:
    """Fused recurrence: xi (N, T, 3H), w_h (H, 3H), b_h (3H,) -> last
    hidden state (N, H)."""
    n_rows, t_len, h3 = xi.shape
    h_dim = h3 // 3
    nb, n_pad, grid = _block_setup(n_rows, t_len, h_dim)
    xs, ws, bs = _split_gates(xi, w_h, b_h, n_pad)
    return _forward_impl(xs, ws, bs, n_rows, t_len, h_dim, nb, n_pad, grid)


def _fwd(xi, w_h, b_h):
    # Residuals carry the already-split time-leading gate streams so the
    # backward never re-does the (N, T, 3H) -> 3x(T, N+pad, H) relayout.
    n_rows, t_len, h3 = xi.shape
    h_dim = h3 // 3
    nb, n_pad, grid = _block_setup(n_rows, t_len, h_dim)
    xs, ws, bs = _split_gates(xi, w_h, b_h, n_pad)
    out = _forward_impl(xs, ws, bs, n_rows, t_len, h_dim, nb, n_pad, grid)
    return out, (xs, ws, bs, n_rows)


def _bwd(res, dh):
    xs, ws, bs, n_rows = res
    interpret = jax.default_backend() != "tpu"
    t_len, n_padded, h_dim = xs[0].shape
    nb, n_pad, grid = _block_setup(n_rows, t_len, h_dim)
    dh_in = dh.astype(jnp.float32)
    if n_pad:
        dh_in = jnp.pad(dh_in, ((0, n_pad), (0, 0)))

    x_spec, w_spec, b_spec = _specs(t_len, nb, h_dim)
    outs = pl.pallas_call(
        _bwd_kernel,
        grid=grid,
        in_specs=[x_spec] * 3 + [w_spec] * 3 + [b_spec] * 3 + [
            pl.BlockSpec((nb, h_dim), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[x_spec] * 3 + [w_spec] * 3 + [b_spec] * 3,
        out_shape=(
            [jax.ShapeDtypeStruct((t_len, n_rows + n_pad, h_dim),
                                  jnp.float32)] * 3
            + [jax.ShapeDtypeStruct((h_dim, h_dim), jnp.float32)] * 3
            + [jax.ShapeDtypeStruct((1, h_dim), jnp.float32)] * 3
        ),
        scratch_shapes=[
            pltpu.VMEM((t_len + 1, nb, h_dim), jnp.float32),
        ],
        interpret=interpret,
    )(*xs, *ws, *bs, dh_in)
    dxr, dxz, dxn, dwhr, dwhz, dwhn, dbr, dbz, dbn = outs
    # reassemble the packed [r | z | n] layouts
    dxi = jnp.concatenate([dxr, dxz, dxn], axis=-1)       # (T, N+pad, 3H)
    dxi = jnp.transpose(dxi, (1, 0, 2))[:n_rows]
    dwh = jnp.concatenate([dwhr, dwhz, dwhn], axis=1)
    dbh = jnp.concatenate([dbr[0], dbz[0], dbn[0]])
    return dxi, dwh, dbh


gru_scan.defvjp(_fwd, _bwd)
