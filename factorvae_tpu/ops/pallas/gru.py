"""Fused GRU recurrence as a Pallas TPU kernel (forward + custom VJP).

The extractor's GRU runs as `lax.scan` over T (models/layers.py) — already
good under XLA. This kernel fuses the *whole recurrence* into one Pallas
call: the precomputed input projections `xi` (N, T, 3H), the hidden
weights and the running hidden state all stay in VMEM for all T steps, so
nothing round-trips HBM between timesteps. The input-side projection (one
big matmul) deliberately stays OUTSIDE the kernel where the MXU already
handles it optimally.

Backward is a second kernel doing recompute-BPTT: re-run the recurrence
storing the (T+1, Nb, H) hidden sequence in VMEM, then walk t = T-1..0
accumulating d_xi, d_Wh, d_bh and the carried d_h.

Rows (stocks) are independent in the recurrence, so both kernels tile the
N axis into blocks of `_N_BLOCK` rows per grid step — bounding VMEM to a
few MB regardless of N and T (the backward's per-block footprint is
xi + dxi + h-seq ≈ 2*Nb*T*3H + (T+1)*Nb*H floats; at Nb=64, T=60, H=64
that is ~7 MB). d_Wh/d_bh accumulate across the sequential TPU grid.

Gate math matches layers.GRU exactly (torch layout [r | z | n]):

    r = sigmoid(xi_r + gh_r)    z = sigmoid(xi_z + gh_z)
    n = tanh(xi_n + r * gh_n)   h' = (1 - z) * n + z * h
    with gh = h @ Wh + bh

Selected via ``ModelConfig.use_pallas_gru``; interpret-mode on CPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_N_BLOCK = 64  # rows per grid step; bounds VMEM independent of N/T


def _gates(xt, gh, h_dim):
    r = jax.nn.sigmoid(xt[:, :h_dim] + gh[:, :h_dim])
    z = jax.nn.sigmoid(xt[:, h_dim:2 * h_dim] + gh[:, h_dim:2 * h_dim])
    n = jnp.tanh(xt[:, 2 * h_dim:] + r * gh[:, 2 * h_dim:])
    return r, z, n


def _fwd_kernel(xi_ref, wh_ref, bh_ref, hlast_ref):
    xi = xi_ref[:]                                   # (N, T, 3H)
    wh = wh_ref[:]                                   # (H, 3H)
    bh = bh_ref[0, :]                                # (3H,)
    n_rows, t_len, h3 = xi.shape
    h_dim = h3 // 3

    def step(t, h):
        xt = jax.lax.dynamic_slice_in_dim(xi, t, 1, axis=1)[:, 0, :]
        gh = jnp.dot(h, wh, preferred_element_type=jnp.float32) + bh
        r, z, n = _gates(xt, gh, h_dim)
        return (1.0 - z) * n + z * h

    h0 = jnp.zeros((n_rows, h_dim), jnp.float32)
    hlast_ref[:] = jax.lax.fori_loop(0, t_len, step, h0)


def _bwd_kernel(xi_ref, wh_ref, bh_ref, dh_ref, dxi_ref, dwh_ref, dbh_ref):
    xi = xi_ref[:]
    wh = wh_ref[:]
    bh = bh_ref[0, :]
    n_rows, t_len, h3 = xi.shape
    h_dim = h3 // 3

    # recompute the hidden sequence: hseq[t] = h before step t
    def fstep(t, hseq):
        h = jax.lax.dynamic_slice_in_dim(hseq, t, 1, axis=0)[0]
        xt = jax.lax.dynamic_slice_in_dim(xi, t, 1, axis=1)[:, 0, :]
        gh = jnp.dot(h, wh, preferred_element_type=jnp.float32) + bh
        r, z, n = _gates(xt, gh, h_dim)
        h_new = (1.0 - z) * n + z * h
        return jax.lax.dynamic_update_slice(hseq, h_new[None], (t + 1, 0, 0))

    hseq = jnp.zeros((t_len + 1, n_rows, h_dim), jnp.float32)
    hseq = jax.lax.fori_loop(0, t_len, fstep, hseq)

    def bstep(i, carry):
        dh, dxi, dwh, dbh = carry
        t = t_len - 1 - i
        h_prev = jax.lax.dynamic_slice_in_dim(hseq, t, 1, axis=0)[0]
        xt = jax.lax.dynamic_slice_in_dim(xi, t, 1, axis=1)[:, 0, :]
        gh = jnp.dot(h_prev, wh, preferred_element_type=jnp.float32) + bh
        r, z, n = _gates(xt, gh, h_dim)
        # h' = (1-z) n + z h_prev
        dz = dh * (h_prev - n)
        dn = dh * (1.0 - z)
        dh_prev = dh * z
        dtanh = dn * (1.0 - n * n)               # d(xi_n + r*gh_n)
        dr = dtanh * gh[:, 2 * h_dim:]
        dgh_n = dtanh * r
        dsig_r = dr * r * (1.0 - r)              # d(xi_r + gh_r)
        dsig_z = dz * z * (1.0 - z)              # d(xi_z + gh_z)
        dxt = jnp.concatenate([dsig_r, dsig_z, dtanh], axis=-1)   # (Nb, 3H)
        dgh = jnp.concatenate([dsig_r, dsig_z, dgh_n], axis=-1)   # (Nb, 3H)
        dh_prev = dh_prev + jnp.dot(
            dgh, wh.T, preferred_element_type=jnp.float32
        )
        dwh = dwh + jnp.dot(h_prev.T, dgh, preferred_element_type=jnp.float32)
        dbh = dbh + jnp.sum(dgh, axis=0)
        dxi = jax.lax.dynamic_update_slice(dxi, dxt[:, None, :], (0, t, 0))
        return dh_prev, dxi, dwh, dbh

    init = (
        dh_ref[:],
        jnp.zeros((n_rows, t_len, h3), jnp.float32),
        jnp.zeros((h_dim, h3), jnp.float32),
        jnp.zeros((h3,), jnp.float32),
    )
    _, dxi, dwh, dbh = jax.lax.fori_loop(0, t_len, bstep, init)
    dxi_ref[:] = dxi

    # dWh/dbh accumulate across the sequential grid of row blocks
    @pl.when(pl.program_id(0) == 0)
    def _init():
        dwh_ref[:] = jnp.zeros_like(dwh_ref)
        dbh_ref[:] = jnp.zeros_like(dbh_ref)

    dwh_ref[:] += dwh
    dbh_ref[0, :] += dbh


def _pad_rows(a: jnp.ndarray, n_pad: int) -> jnp.ndarray:
    if n_pad == 0:
        return a
    pad = [(0, n_pad)] + [(0, 0)] * (a.ndim - 1)
    return jnp.pad(a, pad)


@jax.custom_vjp
def gru_scan(xi: jnp.ndarray, w_h: jnp.ndarray, b_h: jnp.ndarray) -> jnp.ndarray:
    """Fused recurrence: xi (N, T, 3H), w_h (H, 3H), b_h (3H,) -> last
    hidden state (N, H)."""
    interpret = jax.default_backend() != "tpu"
    n_rows, t_len, h3 = xi.shape
    h_dim = h3 // 3
    nb = min(_N_BLOCK, n_rows)
    n_pad = (-n_rows) % nb
    grid = ((n_rows + n_pad) // nb,)
    out = pl.pallas_call(
        _fwd_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((nb, t_len, h3), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((h_dim, h3), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, h3), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((nb, h_dim), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_rows + n_pad, h_dim), jnp.float32),
        interpret=interpret,
    )(_pad_rows(xi.astype(jnp.float32), n_pad), w_h.astype(jnp.float32),
      b_h.reshape(1, -1).astype(jnp.float32))
    return out[:n_rows]


def _fwd(xi, w_h, b_h):
    return gru_scan(xi, w_h, b_h), (xi, w_h, b_h)


def _bwd(res, dh):
    xi, w_h, b_h = res
    interpret = jax.default_backend() != "tpu"
    n_rows, t_len, h3 = xi.shape
    h_dim = h3 // 3
    nb = min(_N_BLOCK, n_rows)
    n_pad = (-n_rows) % nb
    grid = ((n_rows + n_pad) // nb,)
    dxi, dwh, dbh = pl.pallas_call(
        _bwd_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((nb, t_len, h3), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((h_dim, h3), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, h3), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((nb, h_dim), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((nb, t_len, h3), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((h_dim, h3), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, h3), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_rows + n_pad, t_len, h3), jnp.float32),
            jax.ShapeDtypeStruct((h_dim, h3), jnp.float32),
            jax.ShapeDtypeStruct((1, h3), jnp.float32),
        ],
        interpret=interpret,
    )(_pad_rows(xi.astype(jnp.float32), n_pad), w_h.astype(jnp.float32),
      b_h.reshape(1, -1).astype(jnp.float32),
      _pad_rows(dh.astype(jnp.float32), n_pad))
    return dxi[:n_rows], dwh, dbh[0]


gru_scan.defvjp(_fwd, _bwd)
