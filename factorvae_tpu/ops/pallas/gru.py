"""Fused GRU recurrence as a Pallas TPU kernel (forward + custom VJP).

The extractor's GRU runs as `lax.scan` over T (models/layers.py) — already
good under XLA. This kernel fuses the *whole recurrence* into one Pallas
call: the precomputed input projections, the hidden weights and the
running hidden state all stay in VMEM for all T steps, so nothing
round-trips HBM between timesteps. The input-side projection (one big
matmul) deliberately stays OUTSIDE the kernel where the MXU already
handles it optimally.

Mosaic-compatibility notes (the round-1 kernel compiled only in
interpret mode; VERDICT r1 item 3):
- The Pallas TPU lowering has no `dynamic_slice` on *values*, so the
  per-timestep read is a dynamic **ref** load (`ref[pl.ds(t, 1)]`) on a
  time-LEADING layout — dynamic indexing is only cheap/legal on leading
  dims.
- Gate projections arrive pre-split per gate (r/z/n) instead of one
  (N, T, 3H) block, so the kernel never slices the minor (lane) axis at
  non-128-aligned offsets.
- The backward's recomputed hidden sequence lives in a VMEM scratch ref
  (dynamic stores on values are likewise unsupported).

Backward is recompute-BPTT: re-run the recurrence storing the hidden
sequence in scratch, then walk t backwards accumulating d_x*, d_Wh*,
d_b* and the carried d_h.

Rows (stocks) are independent in the recurrence, so both kernels tile
the N axis into row blocks per grid step, sized by `_block_setup` from
the backward's MEASURED VMEM footprint (see its docstring). At long T
the full-sequence backward is VMEM-bound (T=60/H=64 forced 24-row
blocks, costing 1.6x vs XLA in the round-2 race), so for T > _SEG_MAX
the backward switches to SEGMENT-CHECKPOINTED BPTT: a cheap XLA scan
precomputes the hidden state at segment boundaries, then a 2-D-grid
kernel (row blocks x time segments in REVERSE order) recomputes and
differentiates one (S, Nb, H) segment at a time, carrying d_h across
segment iterations in persistent VMEM scratch. VMEM then scales with S
instead of T, restoring wide row blocks at any sequence length — the
long-context move (gradient checkpointing inside the kernel) applied to
the stock-panel GRU. d_Wh/d_b accumulate across the whole sequential
grid either way.

Gate math matches layers.GRU exactly (torch layout [r | z | n]):

    r = sigmoid(x_r + h Wh_r + b_r)    z = sigmoid(x_z + h Wh_z + b_z)
    n = tanh(x_n + r * (h Wh_n + b_n))
    h' = (1 - z) * n + z * h

Selected via ``ModelConfig.use_pallas_gru``; interpret-mode off-TPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from factorvae_tpu.ops.pallas.compat import CompilerParams as _CompilerParams

_N_BLOCK = 64        # max rows per grid step
_VMEM_BUDGET = 12 * 2 ** 20   # target bytes for the backward's refs
# (the v5e scoped-vmem limit is 16 MB; leave headroom for the compiler)
_SEG_MAX = 24        # longest sequence the backward holds whole in VMEM;
# beyond it, time is chunked into segments of at most this length
_SEG_MIN = 8         # shortest segment worth the per-segment overhead


def _load_t(ref, t):
    """(T, Nb, H) ref -> (Nb, H) timestep t (dynamic leading-dim load)."""
    return ref[pl.ds(t, 1), :, :][0]


def _fwd_kernel(xr_ref, xz_ref, xn_ref, whr_ref, whz_ref, whn_ref,
                br_ref, bz_ref, bn_ref, hlast_ref):
    t_len, nb, h_dim = xr_ref.shape
    whr, whz, whn = whr_ref[:], whz_ref[:], whn_ref[:]
    br, bz, bn = br_ref[0, :], bz_ref[0, :], bn_ref[0, :]

    def step(t, h):
        ghr = jnp.dot(h, whr, preferred_element_type=jnp.float32) + br
        ghz = jnp.dot(h, whz, preferred_element_type=jnp.float32) + bz
        ghn = jnp.dot(h, whn, preferred_element_type=jnp.float32) + bn
        r = jax.nn.sigmoid(_load_t(xr_ref, t) + ghr)
        z = jax.nn.sigmoid(_load_t(xz_ref, t) + ghz)
        n = jnp.tanh(_load_t(xn_ref, t) + r * ghn)
        return (1.0 - z) * n + z * h

    h0 = jnp.zeros((nb, h_dim), jnp.float32)
    hlast_ref[:] = jax.lax.fori_loop(0, t_len, step, h0)


def _recompute_segment(hseq_ref, h0, xr_ref, xz_ref, xn_ref, ws, bs,
                       s_len):
    """Refill `hseq_ref` with the hidden states of one segment:
    hseq[t] = h BEFORE step t, starting from h0 (zeros for the full
    sequence, the boundary checkpoint for a segment)."""
    whr, whz, whn = ws
    br, bz, bn = bs
    hseq_ref[0] = h0

    def fstep(t, _):
        h = _load_t(hseq_ref, t)
        ghr = jnp.dot(h, whr, preferred_element_type=jnp.float32) + br
        ghz = jnp.dot(h, whz, preferred_element_type=jnp.float32) + bz
        ghn = jnp.dot(h, whn, preferred_element_type=jnp.float32) + bn
        r = jax.nn.sigmoid(_load_t(xr_ref, t) + ghr)
        z = jax.nn.sigmoid(_load_t(xz_ref, t) + ghz)
        n = jnp.tanh(_load_t(xn_ref, t) + r * ghn)
        hseq_ref[pl.ds(t + 1, 1), :, :] = ((1.0 - z) * n + z * h)[None]
        return 0

    jax.lax.fori_loop(0, s_len, fstep, 0)


def _backward_walk(dh0, hseq_ref, xr_ref, xz_ref, xn_ref,
                   dxr_ref, dxz_ref, dxn_ref, ws, bs, s_len):
    """Walk t = s_len-1..0 writing d_x* blocks and returning
    (d_h_before_segment, dWh_r, dWh_z, dWh_n, db_r, db_z, db_n) local
    accumulations. The single home of the hand-derived gate VJP — both
    backward kernels call this."""
    whr, whz, whn = ws
    br, bz, bn = bs
    h_dim = whr.shape[0]

    def bstep(i, carry):
        dh, dwhr, dwhz, dwhn, dbr, dbz, dbn = carry
        t = s_len - 1 - i
        h_prev = _load_t(hseq_ref, t)
        ghr = jnp.dot(h_prev, whr, preferred_element_type=jnp.float32) + br
        ghz = jnp.dot(h_prev, whz, preferred_element_type=jnp.float32) + bz
        ghn = jnp.dot(h_prev, whn, preferred_element_type=jnp.float32) + bn
        r = jax.nn.sigmoid(_load_t(xr_ref, t) + ghr)
        z = jax.nn.sigmoid(_load_t(xz_ref, t) + ghz)
        n = jnp.tanh(_load_t(xn_ref, t) + r * ghn)
        # h' = (1-z) n + z h_prev
        dz = dh * (h_prev - n)
        dn = dh * (1.0 - z)
        dh_prev = dh * z
        dtanh = dn * (1.0 - n * n)               # d(x_n + r*ghn)
        dr = dtanh * ghn
        dghn = dtanh * r
        dghr = dr * r * (1.0 - r)                # d(x_r + ghr)
        dghz = dz * z * (1.0 - z)                # d(x_z + ghz)
        dxr_ref[pl.ds(t, 1), :, :] = dghr[None]
        dxz_ref[pl.ds(t, 1), :, :] = dghz[None]
        dxn_ref[pl.ds(t, 1), :, :] = dtanh[None]
        dh_prev = dh_prev + (
            jnp.dot(dghr, whr.T, preferred_element_type=jnp.float32)
            + jnp.dot(dghz, whz.T, preferred_element_type=jnp.float32)
            + jnp.dot(dghn, whn.T, preferred_element_type=jnp.float32)
        )
        dwhr = dwhr + jnp.dot(h_prev.T, dghr,
                              preferred_element_type=jnp.float32)
        dwhz = dwhz + jnp.dot(h_prev.T, dghz,
                              preferred_element_type=jnp.float32)
        dwhn = dwhn + jnp.dot(h_prev.T, dghn,
                              preferred_element_type=jnp.float32)
        dbr = dbr + jnp.sum(dghr, axis=0, keepdims=True)
        dbz = dbz + jnp.sum(dghz, axis=0, keepdims=True)
        dbn = dbn + jnp.sum(dghn, axis=0, keepdims=True)
        return dh_prev, dwhr, dwhz, dwhn, dbr, dbz, dbn

    zero_w = jnp.zeros((h_dim, h_dim), jnp.float32)
    zero_b = jnp.zeros((1, h_dim), jnp.float32)
    init = (dh0, zero_w, zero_w, zero_w, zero_b, zero_b, zero_b)
    return jax.lax.fori_loop(0, s_len, bstep, init)


def _accumulate_weight_grads(first, refs, vals):
    """dWh/db accumulate across the whole sequential grid; `first` marks
    the very first grid iteration (zero-init)."""

    @pl.when(first)
    def _init():
        for ref in refs:
            ref[:] = jnp.zeros_like(ref)

    for ref, val in zip(refs, vals):
        ref[:] += val


def _bwd_kernel(xr_ref, xz_ref, xn_ref, whr_ref, whz_ref, whn_ref,
                br_ref, bz_ref, bn_ref, dh_ref,
                dxr_ref, dxz_ref, dxn_ref,
                dwhr_ref, dwhz_ref, dwhn_ref,
                dbr_ref, dbz_ref, dbn_ref,
                hseq_ref):
    """Full-sequence backward: recompute all T hidden states into
    scratch, then one backward walk. Grid = (row blocks,)."""
    t_len, nb, h_dim = xr_ref.shape
    ws = (whr_ref[:], whz_ref[:], whn_ref[:])
    bs = (br_ref[0, :], bz_ref[0, :], bn_ref[0, :])

    _recompute_segment(hseq_ref, jnp.zeros((nb, h_dim), jnp.float32),
                       xr_ref, xz_ref, xn_ref, ws, bs, t_len)
    _, dwhr, dwhz, dwhn, dbr, dbz, dbn = _backward_walk(
        dh_ref[:], hseq_ref, xr_ref, xz_ref, xn_ref,
        dxr_ref, dxz_ref, dxn_ref, ws, bs, t_len)
    _accumulate_weight_grads(
        pl.program_id(0) == 0,
        (dwhr_ref, dwhz_ref, dwhn_ref, dbr_ref, dbz_ref, dbn_ref),
        (dwhr, dwhz, dwhn, dbr, dbz, dbn))


def _bwd_seg_kernel(xr_ref, xz_ref, xn_ref, whr_ref, whz_ref,
                    whn_ref, br_ref, bz_ref, bn_ref, dh_ref, hck_ref,
                    dxr_ref, dxz_ref, dxn_ref,
                    dwhr_ref, dwhz_ref, dwhn_ref,
                    dbr_ref, dbz_ref, dbn_ref,
                    hseq_ref, carry_ref):
    """One (row block, time segment) backward step. Grid is
    (n_blocks, n_segs) with segments visited in REVERSE time order (the
    index maps flip s); `carry_ref` holds d_h flowing from segment
    seg+1 down to seg across grid iterations (TPU grids run
    sequentially, which the accumulators already rely on)."""
    s_len, nb, h_dim = xr_ref.shape
    ws = (whr_ref[:], whz_ref[:], whn_ref[:])
    bs = (br_ref[0, :], bz_ref[0, :], bn_ref[0, :])

    # the first segment iteration of each row block is the LAST time
    # segment: seed the carry with the incoming d_h for these rows
    @pl.when(pl.program_id(1) == 0)
    def _seed():
        carry_ref[:] = dh_ref[:]

    # recompute this segment's hidden sequence from its checkpoint
    _recompute_segment(hseq_ref, hck_ref[0], xr_ref, xz_ref, xn_ref,
                       ws, bs, s_len)
    dh_out, dwhr, dwhz, dwhn, dbr, dbz, dbn = _backward_walk(
        carry_ref[:], hseq_ref, xr_ref, xz_ref, xn_ref,
        dxr_ref, dxz_ref, dxn_ref, ws, bs, s_len)
    carry_ref[:] = dh_out
    _accumulate_weight_grads(
        jnp.logical_and(pl.program_id(0) == 0, pl.program_id(1) == 0),
        (dwhr_ref, dwhz_ref, dwhn_ref, dbr_ref, dbz_ref, dbn_ref),
        (dwhr, dwhz, dwhn, dbr, dbz, dbn))


def _split_gates(xi: jnp.ndarray, w_h: jnp.ndarray, b_h: jnp.ndarray,
                 n_pad: int):
    """(N, T, 3H) -> three time-leading (T, N+pad, H) gate streams plus
    per-gate weights/biases (torch layout [r | z | n])."""
    h_dim = w_h.shape[0]
    xs, ws, bs = [], [], []
    for g in range(3):
        x = xi[:, :, g * h_dim:(g + 1) * h_dim].astype(jnp.float32)
        x = jnp.transpose(x, (1, 0, 2))              # (T, N, H)
        if n_pad:
            x = jnp.pad(x, ((0, 0), (0, n_pad), (0, 0)))
        xs.append(x)
        ws.append(w_h[:, g * h_dim:(g + 1) * h_dim].astype(jnp.float32))
        bs.append(b_h[g * h_dim:(g + 1) * h_dim].reshape(1, -1)
                  .astype(jnp.float32))
    return xs, ws, bs


def _rows_blocking(n_rows: int, per_row: int):
    """Shared row-block derivation: clamp to the VMEM budget (8-row
    aligned, capped at _N_BLOCK), pad the row count to a multiple.
    `per_row` is each path's measured VMEM bytes per row (the analytic
    ref count times the ~2x empirical Mosaic scoped-allocation factor —
    measured r2 on v5e at T=60/H=64: nb=64 allocated 24.41 MB and nb=48
    18.30 MB against a 16 MB limit, i.e. ~0.38 MB/row vs the analytic
    0.20 MB/row)."""
    nb = max(8, min(_N_BLOCK, (_VMEM_BUDGET // per_row) // 8 * 8))
    nb = min(nb, n_rows) if n_rows >= 8 else n_rows
    n_pad = (-n_rows) % nb
    return nb, n_pad, (n_rows + n_pad) // nb


def _block_setup(n_rows: int, t_len: int, h_dim: int):
    """Full-sequence backward blocks: six (T, Nb, H) refs
    double-buffered plus the (T+1, Nb, H) scratch. Yields nb=64 at
    T=20/H<=64. (The T=60 full-sequence case that forced nb=24 now
    takes the segmented path instead — see _segment_setup.)"""
    nb, n_pad, n_blocks = _rows_blocking(
        n_rows, 2 * (13 * t_len + 1) * h_dim * 4)
    return nb, n_pad, (n_blocks,)


def _segment_len(t_len: int) -> int:
    """Segment length for the checkpointed backward: the largest divisor
    of T in [_SEG_MIN, _SEG_MAX] (segments tile T exactly and each
    carries enough work to amortize the per-segment hseq refill / carry
    round-trip). When none exists (ADVICE r2: e.g. T = 2 * prime), any
    divisor >= 2 still bounds per-row VMEM by the segment length, so a
    degenerate-but-safe short segment beats the full-sequence path whose
    footprint grows linearly in T; only divisor-free (prime) T falls all
    the way back to T itself (full-sequence path — see `backward_fits`
    for the guard that keeps that fallback inside the VMEM budget)."""
    if t_len <= _SEG_MAX:
        return t_len
    for s in range(_SEG_MAX, 1, -1):
        if t_len % s == 0:
            return s
    return t_len


def backward_fits(n_rows: int, t_len: int, h_dim: int) -> bool:
    """Whether some backward path fits the scoped-VMEM budget at the
    minimum 8-row block (ADVICE r2): the segmented path caps per-row
    bytes by the segment length, but a divisor-free T forces the
    full-sequence path, whose per-row footprint grows linearly in T and
    can exceed the 16 MB scoped-VMEM limit on a real chip even at nb=8.
    Callers (models/layers.py GRU) must fall back to the XLA scan when
    this is False — including under use_pallas=True."""
    del n_rows  # blocking already clamps rows; the floor is 8
    s_len = _segment_len(t_len)
    extra = 3 if s_len < t_len else 1  # checkpoint + carry blocks
    per_row = 2 * (13 * s_len + extra) * h_dim * 4
    return 8 * per_row <= _VMEM_BUDGET


def _segment_setup(n_rows: int, t_len: int, h_dim: int):
    """(s_len, n_segs, nb, n_pad, grid) for the segmented backward: the
    _block_setup VMEM model with T replaced by the segment length (plus
    the tiny (1, Nb, H) checkpoint block and (Nb, H) carry), so row
    blocks stay wide at any T."""
    s_len = _segment_len(t_len)
    n_segs = t_len // s_len
    nb, n_pad, n_blocks = _rows_blocking(
        n_rows, 2 * (13 * s_len + 3) * h_dim * 4)
    return s_len, n_segs, nb, n_pad, (n_blocks, n_segs)


def _segment_checkpoints(xs, ws, bs, s_len: int, n_segs: int):
    """Hidden state at each segment START, (n_segs, N_padded, H), via a
    plain XLA scan over segments (fori over steps inside). One extra
    forward recurrence — the standard cost of gradient checkpointing —
    on the already-relayouted time-leading gate streams."""
    xr, xz, xn = xs
    whr, whz, whn = ws
    br, bz, bn = (b[0] for b in bs)
    n_padded, h_dim = xr.shape[1], xr.shape[2]

    def seg(h, chunk):
        cr, cz, cn = chunk

        hi = jax.lax.Precision.HIGHEST

        def step(t, hh):
            # HIGHEST matches the kernel's f32 in-VMEM recompute — the
            # default TPU precision (bf16-class MXU passes) would drift
            # the boundary states every segment's gradients start from
            ghr = jnp.dot(hh, whr, precision=hi) + br
            ghz = jnp.dot(hh, whz, precision=hi) + bz
            ghn = jnp.dot(hh, whn, precision=hi) + bn
            r = jax.nn.sigmoid(cr[t] + ghr)
            z = jax.nn.sigmoid(cz[t] + ghz)
            n = jnp.tanh(cn[t] + r * ghn)
            return (1.0 - z) * n + z * hh

        h_end = jax.lax.fori_loop(0, s_len, step, h)
        return h_end, h          # emit the state at segment START

    chunks = tuple(
        x.reshape(n_segs, s_len, n_padded, h_dim) for x in (xr, xz, xn)
    )
    h0 = jnp.zeros((n_padded, h_dim), jnp.float32)
    _, h_starts = jax.lax.scan(seg, h0, chunks)
    return h_starts              # (n_segs, N_padded, H)


def _fwd_block_setup(n_rows: int, t_len: int, h_dim: int):
    """Forward-only row blocks: just the three gate streams
    (double-buffered) plus the output live in VMEM, so the forward
    keeps wide blocks even at T=60 where the full-sequence backward
    could not."""
    nb, n_pad, n_blocks = _rows_blocking(
        n_rows, 2 * (6 * t_len + 2) * h_dim * 4)
    return nb, n_pad, (n_blocks,)


def _repad_rows(arrs, target: int):
    """Re-pad/slice time-leading (T, N_padded, H) arrays (and the
    (N_padded, H) d_h) on the row axis to `target` rows. The forward and
    the two backward paths size their row blocks independently, so their
    paddings can differ; padding rows are zeros and produce zero grads."""
    out = []
    for a in arrs:
        axis = a.ndim - 2
        cur = a.shape[axis]
        if cur < target:
            pad = [(0, 0)] * a.ndim
            pad[axis] = (0, target - cur)
            a = jnp.pad(a, pad)
        elif cur > target:
            a = jax.lax.slice_in_dim(a, 0, target, axis=axis)
        out.append(a)
    return out


def _prep_bwd_inputs(xs, dh, n_rows: int, n_pad: int):
    """Shared backward preamble: f32 cotangent + reconcile the forward's
    row padding with this backward path's own blocking."""
    dh_in = dh.astype(jnp.float32)
    target = n_rows + n_pad
    if target != xs[0].shape[1] or target != dh_in.shape[0]:
        xs = _repad_rows(xs, target)
        (dh_in,) = _repad_rows([dh_in], target)
    return xs, dh_in


def _specs(t_len: int, nb: int, h_dim: int):
    x_spec = pl.BlockSpec((t_len, nb, h_dim), lambda i: (0, i, 0),
                          memory_space=pltpu.VMEM)
    w_spec = pl.BlockSpec((h_dim, h_dim), lambda i: (0, 0),
                          memory_space=pltpu.VMEM)
    b_spec = pl.BlockSpec((1, h_dim), lambda i: (0, 0),
                          memory_space=pltpu.VMEM)
    return x_spec, w_spec, b_spec


def _forward_impl(xs, ws, bs, n_rows, t_len, h_dim, nb, n_pad, grid):
    interpret = jax.default_backend() != "tpu"
    x_spec, w_spec, b_spec = _specs(t_len, nb, h_dim)
    out = pl.pallas_call(
        _fwd_kernel,
        grid=grid,
        in_specs=[x_spec] * 3 + [w_spec] * 3 + [b_spec] * 3,
        out_specs=pl.BlockSpec((nb, h_dim), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_rows + n_pad, h_dim), jnp.float32),
        # row blocks are independent: a megacore TPU may split them
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=interpret,
    )(*xs, *ws, *bs)
    return out[:n_rows]


@jax.custom_vjp
def gru_scan(xi: jnp.ndarray, w_h: jnp.ndarray, b_h: jnp.ndarray) -> jnp.ndarray:
    """Fused recurrence: xi (N, T, 3H), w_h (H, 3H), b_h (3H,) -> last
    hidden state (N, H)."""
    n_rows, t_len, h3 = xi.shape
    h_dim = h3 // 3
    nb, n_pad, grid = _fwd_block_setup(n_rows, t_len, h_dim)
    xs, ws, bs = _split_gates(xi, w_h, b_h, n_pad)
    return _forward_impl(xs, ws, bs, n_rows, t_len, h_dim, nb, n_pad, grid)


def _fwd(xi, w_h, b_h):
    # Residuals carry the already-split time-leading gate streams so the
    # backward never re-does the (N, T, 3H) -> 3x(T, N+pad, H) relayout.
    n_rows, t_len, h3 = xi.shape
    h_dim = h3 // 3
    nb, n_pad, grid = _fwd_block_setup(n_rows, t_len, h_dim)
    xs, ws, bs = _split_gates(xi, w_h, b_h, n_pad)
    out = _forward_impl(xs, ws, bs, n_rows, t_len, h_dim, nb, n_pad, grid)
    return out, (xs, ws, bs, n_rows)


def _bwd(res, dh):
    xs, ws, bs, n_rows = res
    t_len = xs[0].shape[0]
    if _segment_len(t_len) < t_len:
        return _bwd_segmented(xs, ws, bs, n_rows, dh)
    return _bwd_full(xs, ws, bs, n_rows, dh)


def _finish_bwd(outs, n_rows: int):
    """Reassemble the per-gate kernel outputs into the packed
    [r | z | n] gradients (shared by both backward paths)."""
    dxr, dxz, dxn, dwhr, dwhz, dwhn, dbr, dbz, dbn = outs
    dxi = jnp.concatenate([dxr, dxz, dxn], axis=-1)       # (T, N+pad, 3H)
    dxi = jnp.transpose(dxi, (1, 0, 2))[:n_rows]
    dwh = jnp.concatenate([dwhr, dwhz, dwhn], axis=1)
    dbh = jnp.concatenate([dbr[0], dbz[0], dbn[0]])
    return dxi, dwh, dbh


def _bwd_full(xs, ws, bs, n_rows, dh):
    interpret = jax.default_backend() != "tpu"
    t_len, _, h_dim = xs[0].shape
    nb, n_pad, grid = _block_setup(n_rows, t_len, h_dim)
    xs, dh_in = _prep_bwd_inputs(xs, dh, n_rows, n_pad)

    x_spec, w_spec, b_spec = _specs(t_len, nb, h_dim)
    outs = pl.pallas_call(
        _bwd_kernel,
        grid=grid,
        in_specs=[x_spec] * 3 + [w_spec] * 3 + [b_spec] * 3 + [
            pl.BlockSpec((nb, h_dim), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[x_spec] * 3 + [w_spec] * 3 + [b_spec] * 3,
        out_shape=(
            [jax.ShapeDtypeStruct((t_len, n_rows + n_pad, h_dim),
                                  jnp.float32)] * 3
            + [jax.ShapeDtypeStruct((h_dim, h_dim), jnp.float32)] * 3
            + [jax.ShapeDtypeStruct((1, h_dim), jnp.float32)] * 3
        ),
        scratch_shapes=[
            pltpu.VMEM((t_len + 1, nb, h_dim), jnp.float32),
        ],
        # dWh/db accumulate across row blocks: the grid must stay
        # sequential (no megacore split)
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(*xs, *ws, *bs, dh_in)
    return _finish_bwd(outs, n_rows)


def _bwd_segmented(xs, ws, bs, n_rows, dh):
    """Segment-checkpointed BPTT (see module docstring): XLA scan
    precomputes per-segment boundary states, then a (row blocks x
    reversed time segments) grid differentiates one (S, Nb, H) chunk at
    a time with d_h carried in persistent scratch."""
    interpret = jax.default_backend() != "tpu"
    t_len, _, h_dim = xs[0].shape
    s_len, n_segs, nb, n_pad, grid = _segment_setup(n_rows, t_len, h_dim)
    xs, dh_in = _prep_bwd_inputs(xs, dh, n_rows, n_pad)

    hck = _segment_checkpoints(xs, ws, bs, s_len, n_segs)

    # time segments are visited in reverse: grid step s works on
    # time-block (n_segs - 1 - s)
    seg_x = pl.BlockSpec((s_len, nb, h_dim),
                         lambda i, s: (n_segs - 1 - s, i, 0),
                         memory_space=pltpu.VMEM)
    w_spec = pl.BlockSpec((h_dim, h_dim), lambda i, s: (0, 0),
                          memory_space=pltpu.VMEM)
    b_spec = pl.BlockSpec((1, h_dim), lambda i, s: (0, 0),
                          memory_space=pltpu.VMEM)
    dh_spec = pl.BlockSpec((nb, h_dim), lambda i, s: (i, 0),
                           memory_space=pltpu.VMEM)
    ck_spec = pl.BlockSpec((1, nb, h_dim),
                           lambda i, s: (n_segs - 1 - s, i, 0),
                           memory_space=pltpu.VMEM)

    outs = pl.pallas_call(
        _bwd_seg_kernel,
        grid=grid,
        in_specs=[seg_x] * 3 + [w_spec] * 3 + [b_spec] * 3
        + [dh_spec, ck_spec],
        out_specs=[seg_x] * 3 + [w_spec] * 3 + [b_spec] * 3,
        out_shape=(
            [jax.ShapeDtypeStruct((t_len, n_rows + n_pad, h_dim),
                                  jnp.float32)] * 3
            + [jax.ShapeDtypeStruct((h_dim, h_dim), jnp.float32)] * 3
            + [jax.ShapeDtypeStruct((1, h_dim), jnp.float32)] * 3
        ),
        scratch_shapes=[
            pltpu.VMEM((s_len + 1, nb, h_dim), jnp.float32),
            pltpu.VMEM((nb, h_dim), jnp.float32),
        ],
        # the d_h carry flows across segment iterations and dWh/db
        # accumulate across the whole grid: both axes must stay
        # sequential (no megacore split)
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(*xs, *ws, *bs, dh_in, hck)
    return _finish_bwd(outs, n_rows)


gru_scan.defvjp(_fwd, _bwd)
