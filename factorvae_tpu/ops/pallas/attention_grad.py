"""Differentiable fused cross-section attention (custom VJP).

Makes the Pallas kernel in attention.py usable on the *training* path:
`fused_attention` is a `jax.custom_vjp` whose forward is the fused
per-head kernel and whose backward is a second per-head kernel that
recomputes keys/values/scores from the inputs (flash-attention-style —
nothing but the (K, H) context ever hits HBM between the passes) and
emits gradients for latent, query and all per-head weights.

Per-head backward math (mirrors reference module.py:140-153 semantics:
scores -> ReLU -> masked softmax -> context):

    key = L Wk + bk;  z = key q;  s = z*sc;  r = relu(s);  a = softmax_m(r)
    V = L Wv + bv;    ctx = a^T V

    dV   = a (x) dctx            dWv = L^T dV   dbv = sum_n dV
    da   = V dctx
    dr   = a . (da - sum(a.da))          (masked entries have a = 0)
    dz   = 1[s>0] . dr * sc
    dq   = key^T dz              dkey = dz (x) q
    dWk  = L^T dkey              dbk = sum_n dkey
    dL   = dkey Wk^T + dV Wv^T   (accumulated over heads)

The reference's NaN guard (module.py:149-150) zeroes a poisoned head's
context in the forward; the backward mirrors it by zeroing that head's
gradients. Train-time score dropout (module.py:144) IS supported: the
predictor draws a tiny (K, N) keep-mask from the flax 'dropout' rng
outside the kernel and passes it as `dropout_mask`
(models/predictor.py:55-66); the kernel applies it between the scaled
scores and the ReLU, so this op serves both inference and training.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from factorvae_tpu.ops.pallas.compat import CompilerParams as _CompilerParams

from factorvae_tpu.ops.pallas.attention import (
    _NEG_INF,
    multihead_cross_section_attention,
)


def _bwd_kernel(latent_ref, maskf_ref, dmask_ref, q_ref, wk_ref, bk_ref,
                wv_ref, bv_ref, dctx_ref, dlatent_ref, dq_ref, dwk_ref,
                dbk_ref, dwv_ref, dbv_ref):
    latent = latent_ref[:]                                   # (N, H)
    maskf = maskf_ref[0, :]                                  # (N,)
    dmask = dmask_ref[0, 0, :]                               # (N,) keep/(1-p)
    q = q_ref[0, 0, :]                                       # (H,)
    dctx = dctx_ref[0, 0, :]                                 # (H,)

    key = jnp.dot(latent, wk_ref[0], preferred_element_type=jnp.float32)
    key = key + bk_ref[0, 0, :][None, :]
    h_dim = key.shape[1]
    sc = 1.0 / jnp.sqrt(jnp.float32(h_dim) + 1e-6)
    z = jnp.dot(key, q[:, None], preferred_element_type=jnp.float32)[:, 0]
    s = z * sc * dmask
    r = jnp.maximum(s, 0.0)
    bad = jnp.any(~jnp.isfinite(jnp.where(maskf > 0, r, 0.0)))
    rm = jnp.where(maskf > 0, r, _NEG_INF)
    m = jnp.max(rm)
    ex = jnp.where(maskf > 0, jnp.exp(rm - m), 0.0)
    denom = jnp.sum(ex)
    a = jnp.where(denom > 0, ex / jnp.where(denom > 0, denom, 1.0), 0.0)

    value = jnp.dot(latent, wv_ref[0], preferred_element_type=jnp.float32)
    value = value + bv_ref[0, 0, :][None, :]
    value = jnp.nan_to_num(value)

    zero_head = jnp.where(bad, 0.0, 1.0)
    dv = (a[:, None] * dctx[None, :]) * zero_head            # (N, H)
    da = jnp.dot(value, dctx[:, None],
                 preferred_element_type=jnp.float32)[:, 0] * zero_head
    t = a * da
    dr = t - a * jnp.sum(t)
    dz = jnp.where(s > 0, dr, 0.0) * sc * dmask              # (N,)
    dkey = dz[:, None] * q[None, :]                          # (N, H)

    dq_ref[0, 0, :] = jnp.dot(
        key.T, dz[:, None], preferred_element_type=jnp.float32
    )[:, 0] * zero_head
    dkey = dkey * zero_head
    dwk_ref[0] = jnp.dot(latent.T, dkey, preferred_element_type=jnp.float32)
    dbk_ref[0, 0, :] = jnp.sum(dkey, axis=0)
    dwv_ref[0] = jnp.dot(latent.T, dv, preferred_element_type=jnp.float32)
    dbv_ref[0, 0, :] = jnp.sum(dv, axis=0)

    dl = jnp.dot(dkey, wk_ref[0].T, preferred_element_type=jnp.float32)
    dl = dl + jnp.dot(dv, wv_ref[0].T, preferred_element_type=jnp.float32)

    # TPU grid steps run sequentially: accumulate dlatent across heads
    @pl.when(pl.program_id(0) == 0)
    def _init():
        dlatent_ref[:] = jnp.zeros_like(dlatent_ref)

    dlatent_ref[:] += dl


def _bwd_pallas(latent, maskf, dmask, query, w_key, b_key, w_val, b_val, dctx,
                interpret):
    n, h = latent.shape
    k = query.shape[0]
    vec = pl.BlockSpec((1, 1, h), lambda i: (i, 0, 0),
                       memory_space=pltpu.VMEM)
    mat = pl.BlockSpec((1, h, h), lambda i: (i, 0, 0),
                       memory_space=pltpu.VMEM)
    dlatent, dq, dwk, dbk, dwv, dbv = pl.pallas_call(
        _bwd_kernel,
        grid=(k,),
        in_specs=[
            pl.BlockSpec((n, h), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, n), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            vec, mat, vec, mat, vec, vec,
        ],
        out_specs=[
            pl.BlockSpec((n, h), lambda i: (0, 0), memory_space=pltpu.VMEM),
            vec, mat, vec, mat, vec,
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h), jnp.float32),      # dlatent
            jax.ShapeDtypeStruct((k, 1, h), jnp.float32),   # dquery
            jax.ShapeDtypeStruct((k, h, h), jnp.float32),   # dWk
            jax.ShapeDtypeStruct((k, 1, h), jnp.float32),   # dbk
            jax.ShapeDtypeStruct((k, h, h), jnp.float32),   # dWv
            jax.ShapeDtypeStruct((k, 1, h), jnp.float32),   # dbv
        ],
        # dlatent accumulates across the head grid (program_id(0)==0
        # init + += revisits): must stay sequential (no megacore split)
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(
        latent.astype(jnp.float32),
        maskf.reshape(1, -1).astype(jnp.float32),
        dmask.astype(jnp.float32).reshape(k, 1, n),
        query.astype(jnp.float32).reshape(k, 1, h),
        w_key.astype(jnp.float32),
        b_key.astype(jnp.float32).reshape(k, 1, h),
        w_val.astype(jnp.float32),
        b_val.astype(jnp.float32).reshape(k, 1, h),
        dctx.astype(jnp.float32).reshape(k, 1, h),
    )
    return (dlatent, dq.reshape(k, h), dwk, dbk.reshape(k, h), dwv,
            dbv.reshape(k, h))


@jax.custom_vjp
def fused_attention(latent, maskf, query, w_key, b_key, w_val, b_val,
                    dropout_mask=None):
    """Differentiable fused K-head attention. maskf: (N,) float {0,1};
    dropout_mask: optional (K, N) keep-mask / (1-p) (see attention.py)."""
    return multihead_cross_section_attention(
        latent, maskf > 0, query, w_key, b_key, w_val, b_val,
        dropout_mask=dropout_mask,
    )


def _fwd(latent, maskf, query, w_key, b_key, w_val, b_val, dropout_mask=None):
    out = fused_attention(latent, maskf, query, w_key, b_key, w_val, b_val,
                          dropout_mask)
    return out, (latent, maskf, query, w_key, b_key, w_val, b_val, dropout_mask)


def _bwd(res, dctx):
    latent, maskf, query, w_key, b_key, w_val, b_val, dropout_mask = res
    if dropout_mask is None:
        dropout_mask = jnp.ones((query.shape[0], latent.shape[0]), jnp.float32)
        dmask_grad = None
    else:
        dmask_grad = jnp.zeros_like(dropout_mask)
    interpret = jax.default_backend() != "tpu"
    dlatent, dq, dwk, dbk, dwv, dbv = _bwd_pallas(
        latent, maskf, dropout_mask, query, w_key, b_key, w_val, b_val, dctx,
        interpret,
    )
    return (dlatent, jnp.zeros_like(maskf), dq, dwk, dbk, dwv, dbv, dmask_grad)


fused_attention.defvjp(_fwd, _bwd)
