"""Fused K-head cross-section attention as a Pallas TPU kernel.

The FactorPredictor's hot op (reference module.py:134-153 per head,
module.py:172-178 looped K times): for each of K heads,

    key_k   = latent @ Wk[k] + bk[k]            (N, H)
    value_k = latent @ Wv[k] + bv[k]            (N, H)
    s_k     = key_k @ q[k] / sqrt(H + 1e-6)     (N,)
    a_k     = masked_softmax(relu(s_k))         (N,)   [quirk order kept]
    ctx_k   = a_k @ value_k                     (H,)

The XLA path (models/predictor.py) materializes the (K, N, H) key/value
stacks in HBM (e.g. K=96, N=360, H=64 -> 2 x 8.8 MB per day per
direction). This kernel blocks over heads: each grid step loads only the
shared (N, H) latent (resident across steps) plus one head's (H, H)
weights, computes everything in VMEM, and writes just the (1, H) context
— the intermediate stacks never touch HBM.

This module is the raw forward kernel; training goes through the
`jax.custom_vjp` wrapper in attention_grad.py. Score dropout
(module.py:144) is supported via an external (K, N) keep-mask applied
between the scaled scores and the ReLU. Selected via
``ModelConfig.use_pallas_attention``. The softmax here is the masked
variant with the reference's NaN guard semantics folded in (a fully
masked or non-finite row yields a zero context).
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from factorvae_tpu.ops.pallas.compat import CompilerParams as _CompilerParams

_NEG_INF = -1e30


def _head_kernel(latent_ref, maskf_ref, dmask_ref, q_ref, wk_ref, bk_ref,
                 wv_ref, bv_ref, out_ref):
    """One head per grid step. latent: (N, H), maskf: (1, N) float {0,1},
    dmask: (1, 1, N) dropout keep-mask (pre-scaled by 1/(1-p); all-ones
    at inference), q/bk/bv: (1, 1, H), wk/wv: (1, H, H), out: (1, 1, H).
    Per-head vectors carry a singleton middle axis so their (1, X) blocks
    satisfy Mosaic's block-shape tiling rule (second-to-last block dim
    must divide 8 or equal the array dim)."""
    latent = latent_ref[:]                                   # (N, H)
    maskf = maskf_ref[0, :]                                  # (N,)
    key = jnp.dot(latent, wk_ref[0], preferred_element_type=jnp.float32)
    key = key + bk_ref[0, 0, :][None, :]
    h_dim = key.shape[1]
    scores = jnp.dot(key, q_ref[0, 0, :][:, None],
                     preferred_element_type=jnp.float32)[:, 0]  # (N,)
    scores = scores / jnp.sqrt(jnp.float32(h_dim) + 1e-6)
    scores = scores * dmask_ref[0, 0, :]        # dropout (module.py:144) ...
    scores = jnp.maximum(scores, 0.0)           # ... BEFORE ReLU (module.py:145)
    # reference NaN guard (module.py:149-150): any non-finite valid score
    # zeroes this head's context entirely
    bad = jnp.any(~jnp.isfinite(jnp.where(maskf > 0, scores, 0.0)))
    scores = jnp.where(maskf > 0, scores, _NEG_INF)
    m = jnp.max(scores)
    ex = jnp.where(maskf > 0, jnp.exp(scores - m), 0.0)
    denom = jnp.sum(ex)
    attn = jnp.where(denom > 0, ex / jnp.where(denom > 0, denom, 1.0), 0.0)
    value = jnp.dot(latent, wv_ref[0], preferred_element_type=jnp.float32)
    value = value + bv_ref[0, 0, :][None, :]
    ctx = jnp.dot(attn[None, :], jnp.nan_to_num(value),
                  preferred_element_type=jnp.float32)[0]
    out_ref[0, 0, :] = jnp.where(bad, 0.0, ctx)


def multihead_cross_section_attention(
    latent: jnp.ndarray,   # (N, H)
    mask: jnp.ndarray,     # (N,) bool
    query: jnp.ndarray,    # (K, H)
    w_key: jnp.ndarray,    # (K, H, H)
    b_key: jnp.ndarray,    # (K, H)
    w_val: jnp.ndarray,    # (K, H, H)
    b_val: jnp.ndarray,    # (K, H)
    interpret: bool = None,
    dropout_mask: jnp.ndarray = None,   # (K, N) keep-mask / (1-p); None = off
) -> jnp.ndarray:
    """Returns the (K, H) context stack (reference h_multi, module.py:178).

    interpret=None auto-selects the Pallas interpreter off-TPU (the CPU
    test rig), the compiled kernel on TPU. `dropout_mask`, when given,
    reproduces the reference's score dropout (module.py:144, applied
    before the ReLU): a per-head (K, N) keep-mask pre-scaled by 1/(1-p),
    generated OUTSIDE the kernel with jax.random (tiny array; the big
    (K, N, H) intermediates stay fused in VMEM).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n, h = latent.shape
    k = query.shape[0]
    maskf = mask.astype(jnp.float32)[None, :]                # (1, N)
    if dropout_mask is None:
        dropout_mask = jnp.ones((k, n), jnp.float32)
    grid = (k,)
    vec = pl.BlockSpec((1, 1, h), lambda i: (i, 0, 0),
                       memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        _head_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((n, h), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, n), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            vec,
            pl.BlockSpec((1, h, h), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
            vec,
            pl.BlockSpec((1, h, h), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
            vec,
        ],
        out_specs=pl.BlockSpec((1, 1, h), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((k, 1, h), jnp.float32),
        # heads are independent: a megacore TPU may split them
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=interpret,
    )(
        latent.astype(jnp.float32),
        maskf,
        dropout_mask.astype(jnp.float32).reshape(k, 1, n),
        query.astype(jnp.float32).reshape(k, 1, h),
        w_key.astype(jnp.float32),
        b_key.astype(jnp.float32).reshape(k, 1, h),
        w_val.astype(jnp.float32),
        b_val.astype(jnp.float32).reshape(k, 1, h),
    )
    return out.reshape(k, h)
