"""Weight-only int8 quantization for the scoring path.

The scoring workload (eval/predict.py; reference utils.py:70-93) is
read-only over the parameter tree, so the weights can live in HBM as
int8 with per-output-channel float scales — 4x smaller than f32, 2x
smaller than bf16 — and be dequantized on the fly in VMEM right before
each matmul. At FactorVAE sizes the matmuls are launch/bandwidth-bound,
not FLOP-bound (PERF.md roofline), so shrinking the bytes the MXU must
pull is the lever this path targets; numerics stay in the model's
compute dtype after dequantization, and the quantization error on
symmetric per-channel int8 is ~0.4% of each channel's max weight.

Symmetric scheme: q = round(clip(w / s, ±127)), s = max|w| per output
channel (the LAST axis — Dense kernels are (in, out), GRU hidden kernels
(H, 3H), the predictor's batched key/value stacks (K, H, H)). Biases,
LayerNorm parameters, the attention query and every other small/1-D leaf
stay in float — they are bytes-irrelevant and precision-critical. The
exclusion is by ROLE, not just size: any leaf whose tree path contains
"bias" or "query" is kept float even when it is 2-D and large (at K=96,
H=64 the predictor's query and key/value biases are (96, 64)).

`QTensor` is a registered pytree node, so a quantized parameter tree
passes through `jax.jit` boundaries as (int8, f32) array pairs and the
dequantize happens *inside* the compiled program (XLA fuses it into the
consumer matmul's operand read).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp


@jax.tree_util.register_pytree_node_class
class QTensor:
    """A per-output-channel symmetric int8 tensor: values `q` (int8) and
    scales `s` broadcastable against `q` (f32, 1 along all axes but the
    last)."""

    def __init__(self, q: jnp.ndarray, s: jnp.ndarray):
        self.q = q
        self.s = s

    def tree_flatten(self):
        return (self.q, self.s), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def shape(self):
        return self.q.shape

    def dequantize(self, dtype=jnp.float32) -> jnp.ndarray:
        return self.q.astype(dtype) * self.s.astype(dtype)

    def __repr__(self):
        return f"QTensor(shape={tuple(self.q.shape)}, int8+scales)"


def quantize_tensor(w: jnp.ndarray) -> QTensor:
    """Symmetric per-last-axis-channel int8 quantization."""
    reduce_axes = tuple(range(w.ndim - 1))
    s = jnp.max(jnp.abs(w), axis=reduce_axes, keepdims=True) / 127.0
    s = jnp.where(s == 0.0, 1.0, s).astype(jnp.float32)
    q = jnp.clip(jnp.round(w / s), -127, 127).astype(jnp.int8)
    return QTensor(q, s)


def _is_quantizable(leaf: Any, min_size: int) -> bool:
    return (
        hasattr(leaf, "ndim")
        and leaf.ndim >= 2
        and leaf.size >= min_size
        and jnp.issubdtype(leaf.dtype, jnp.floating)
    )


# Precision-critical roles kept in float regardless of shape: biases add
# directly into activations/attention logits, and the learned query
# (predictor.py, module.py:129 semantics) sets every head's logit scale.
EXCLUDED_PATH_KEYS = ("bias", "query")


def _path_excluded(path) -> bool:
    for entry in path:
        key = str(getattr(entry, "key", getattr(entry, "idx", "")))
        if any(x in key.lower() for x in EXCLUDED_PATH_KEYS):
            return True
    return False


def quantize_params(params, min_size: int = 256):
    """Quantize every >=2-D float leaf with at least `min_size` elements
    to a QTensor — except leaves named as biases/queries (see
    EXCLUDED_PATH_KEYS); leave everything else untouched. Returns a tree
    with the same structure (QTensor nodes expand into (q, s) leaf
    pairs)."""
    return jax.tree_util.tree_map_with_path(
        lambda path, w: (
            quantize_tensor(w)
            if _is_quantizable(w, min_size) and not _path_excluded(path)
            else w
        ),
        params,
    )


def is_quantized(tree) -> bool:
    """True when the tree already holds QTensor leaves — a
    quantize_params output. The serving registry (serve/registry.py)
    quantizes each int8 entry ONCE at admission; the scoring entry
    points use this to skip a per-request re-quantization pass."""
    return any(
        isinstance(leaf, QTensor)
        for leaf in jax.tree_util.tree_leaves(
            tree, is_leaf=lambda x: isinstance(x, QTensor)))


def ensure_quantized(params, min_size: int = 256):
    """quantize_params, idempotently: an already-quantized tree passes
    through untouched (double-quantizing a QTensor tree would wrap the
    scales themselves)."""
    return params if is_quantized(params) else quantize_params(
        params, min_size)


def dequantize_params(qparams, dtype=jnp.float32):
    """Rebuild a dense float tree from a quantize_params output. Safe to
    call inside jit (and that is the intended use: weights cross into
    the compiled program as int8 and inflate in VMEM)."""
    return jax.tree_util.tree_map(
        lambda x: x.dequantize(dtype) if isinstance(x, QTensor) else x,
        qparams,
        is_leaf=lambda x: isinstance(x, QTensor),
    )


def tree_nbytes(tree) -> int:
    """Total bytes of every array leaf (QTensor counts q + s)."""
    return sum(
        leaf.size * leaf.dtype.itemsize
        for leaf in jax.tree_util.tree_leaves(tree)
        if hasattr(leaf, "dtype")
    )
