"""On-device ranking statistics (Rank-IC).

The reference computes Rank-IC on host with scipy: per-day Spearman rank
correlation of prediction vs label, then mean and IR = mean/std
(utils.py:113-129, backtest.ipynb cell 9). Here the same statistic runs
on device over the padded ``(D, N_max)`` score/label arrays.

Ties are resolved by *average ranks*, matching ``scipy.stats.spearmanr``;
this uses an O(N^2) pairwise comparison which is a trivially small
vectorized op at N_max <= 1024 and maps well onto the VPU.
"""

from __future__ import annotations

import jax.numpy as jnp

from factorvae_tpu.ops.masked import masked_mean


def masked_rank(x: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Average ranks (1-based, scipy convention) of `x` over valid entries.

    Invalid entries get rank 0 and must be excluded downstream.
    x, mask: (..., N)
    """
    m = mask.astype(x.dtype)
    xi = x[..., :, None]
    xj = x[..., None, :]
    mj = m[..., None, :]
    less = jnp.sum((xj < xi) * mj, axis=-1)
    equal = jnp.sum((xj == xi) * mj, axis=-1)
    rank = less + 0.5 * (equal + 1.0)
    return rank * m


def masked_pearson(
    x: jnp.ndarray, y: jnp.ndarray, mask: jnp.ndarray, eps: float = 1e-12
) -> jnp.ndarray:
    """Pearson correlation over valid entries of the trailing axis."""
    mx = masked_mean(x, mask, axis=-1)[..., None]
    my = masked_mean(y, mask, axis=-1)[..., None]
    dx = jnp.where(mask, x - mx, 0.0)
    dy = jnp.where(mask, y - my, 0.0)
    cov = jnp.sum(dx * dy, axis=-1)
    vx = jnp.sum(dx * dx, axis=-1)
    vy = jnp.sum(dy * dy, axis=-1)
    # A zero-variance side (constant scores or labels, or <2 valid
    # entries) has no defined correlation: return NaN exactly as
    # scipy.stats.spearmanr does (reference utils.py:120-126), rather
    # than counting the day as IC=0. rank_ic_summary drops NaN days.
    defined = (vx > 0) & (vy > 0)
    return jnp.where(defined, cov / jnp.sqrt(vx * vy + eps), jnp.nan)


def masked_spearman(x: jnp.ndarray, y: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Spearman rank correlation = Pearson on average ranks (scipy semantics,
    reference utils.py:120)."""
    return masked_pearson(masked_rank(x, mask), masked_rank(y, mask), mask)


def rank_ic_series(
    scores: jnp.ndarray, labels: jnp.ndarray, mask: jnp.ndarray
) -> jnp.ndarray:
    """Per-day Rank-IC over a (D, N_max) panel; returns (D,).

    Entries with non-finite labels (e.g. the trailing days of an inference
    panel, where the forward-looking label does not exist) are excluded via
    the mask before calling this.
    """
    return masked_spearman(scores, labels, mask)


def rank_ic_summary(ic: jnp.ndarray, day_mask: jnp.ndarray):
    """Mean Rank-IC and information ratio over valid days.

    Matches reference utils.py:126-129: IR = mean/std with the *population*
    std (numpy default ddof=0). Non-finite ICs (degenerate days — see
    masked_pearson) are excluded from both moments, mirroring how scipy's
    NaN would simply be dropped from a well-formed evaluation.
    """
    day_mask = day_mask & jnp.isfinite(ic)
    ic = jnp.where(day_mask, ic, 0.0)
    # No defined day at all -> NaN mean (a mean over the empty set), not a
    # plausible-looking 0.0 that would masquerade as "uncorrelated".
    any_valid = jnp.any(day_mask)
    mean = jnp.where(any_valid, masked_mean(ic, day_mask), jnp.nan)
    var = masked_mean((ic - mean) ** 2, day_mask)
    std = jnp.sqrt(var)
    ir = jnp.where(std > 0, mean / jnp.where(std > 0, std, 1.0), jnp.nan)
    return mean, ir
