from factorvae_tpu.ops.kl import gaussian_kl, gaussian_kl_sum
from factorvae_tpu.ops.masked import (
    masked_mean,
    masked_mse,
    masked_softmax,
    masked_gaussian_nll,
)
from factorvae_tpu.ops.quant import (
    QTensor,
    dequantize_params,
    quantize_params,
    quantize_tensor,
    tree_nbytes,
)
from factorvae_tpu.ops.stats import masked_rank, masked_spearman, rank_ic_series

__all__ = [
    "QTensor",
    "dequantize_params",
    "quantize_params",
    "quantize_tensor",
    "tree_nbytes",
    "gaussian_kl",
    "gaussian_kl_sum",
    "masked_mean",
    "masked_mse",
    "masked_softmax",
    "masked_gaussian_nll",
    "masked_rank",
    "masked_spearman",
    "rank_ic_series",
]
