from factorvae_tpu.ops.kl import gaussian_kl, gaussian_kl_sum
from factorvae_tpu.ops.masked import (
    masked_mean,
    masked_mse,
    masked_softmax,
    masked_gaussian_nll,
)
from factorvae_tpu.ops.stats import masked_rank, masked_spearman, rank_ic_series

__all__ = [
    "gaussian_kl",
    "gaussian_kl_sum",
    "masked_mean",
    "masked_mse",
    "masked_softmax",
    "masked_gaussian_nll",
    "masked_rank",
    "masked_spearman",
    "rank_ic_series",
]
