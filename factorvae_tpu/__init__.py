"""factorvae_tpu — a TPU-native (JAX/XLA/Flax/pjit) FactorVAE framework.

A ground-up re-design of the capabilities of the reference PyTorch
implementation (x7jeon8gi/FactorVAE, "FactorVAE: A Probabilistic Dynamic
Factor Model Based on Variational Autoencoder for Predicting Cross-Sectional
Stock Returns", Duan et al., AAAI 2022) for TPU hardware:

- static padded cross-sections + validity masks instead of variable-size
  per-day batches (reference: dataset.py:207-238)
- one batched einsum for the K attention heads instead of a Python loop of
  K modules (reference: module.py:172-178)
- GRU as a `lax.scan` with the input projection hoisted into one big matmul
- whole-epoch `lax.scan` training with on-device metrics (no per-step host
  sync; reference syncs every step at train_model.py:28)
- day-level data parallelism + optional cross-section model parallelism over
  a `jax.sharding.Mesh`, gradients reduced by XLA collectives over ICI
"""

from factorvae_tpu.config import (
    Config,
    DataConfig,
    MeshConfig,
    ModelConfig,
    TrainConfig,
)
from factorvae_tpu.models import (
    AlphaLayer,
    BetaLayer,
    FactorDecoder,
    FactorEncoder,
    FactorPredictor,
    FactorVAE,
    FactorVAEOutput,
    FeatureExtractor,
)

__version__ = "0.1.0"

__all__ = [
    "Config",
    "DataConfig",
    "MeshConfig",
    "ModelConfig",
    "TrainConfig",
    "AlphaLayer",
    "BetaLayer",
    "FactorDecoder",
    "FactorEncoder",
    "FactorPredictor",
    "FactorVAE",
    "FactorVAEOutput",
    "FeatureExtractor",
]
