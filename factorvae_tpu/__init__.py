"""factorvae_tpu — a TPU-native (JAX/XLA/Flax/pjit) FactorVAE framework.

A ground-up re-design of the capabilities of the reference PyTorch
implementation (x7jeon8gi/FactorVAE, "FactorVAE: A Probabilistic Dynamic
Factor Model Based on Variational Autoencoder for Predicting Cross-Sectional
Stock Returns", Duan et al., AAAI 2022) for TPU hardware:

- static padded cross-sections + validity masks instead of variable-size
  per-day batches (reference: dataset.py:207-238)
- one batched einsum for the K attention heads instead of a Python loop of
  K modules (reference: module.py:172-178)
- GRU as a `lax.scan` with the input projection hoisted into one big matmul
- whole-epoch `lax.scan` training with on-device metrics (no per-step host
  sync; reference syncs every step at train_model.py:28)
- day-level data parallelism + optional cross-section model parallelism over
  a `jax.sharding.Mesh`, gradients reduced by XLA collectives over ICI
"""

from factorvae_tpu.config import (
    Config,
    DataConfig,
    MeshConfig,
    ModelConfig,
    TrainConfig,
)
from factorvae_tpu.models import (
    AlphaLayer,
    BetaLayer,
    FactorDecoder,
    FactorEncoder,
    FactorPredictor,
    FactorVAE,
    FactorVAEOutput,
    FeatureExtractor,
)


def __getattr__(name):
    """Lazy top-level conveniences (avoid importing heavy deps eagerly):
    Trainer, PanelDataset, build_panel, load_frame, load_model,
    generate_prediction_scores, RankIC, topk_dropout_backtest, get_preset.
    """
    lazy = {
        "Trainer": ("factorvae_tpu.train.trainer", "Trainer"),
        "PanelDataset": ("factorvae_tpu.data.loader", "PanelDataset"),
        "build_panel": ("factorvae_tpu.data.panel", "build_panel"),
        "load_frame": ("factorvae_tpu.data.panel", "load_frame"),
        "load_model": ("factorvae_tpu.models.factorvae", "load_model"),
        "generate_prediction_scores": (
            "factorvae_tpu.eval.predict", "generate_prediction_scores"),
        "RankIC": ("factorvae_tpu.eval.metrics", "RankIC"),
        "topk_dropout_backtest": (
            "factorvae_tpu.eval.backtest", "topk_dropout_backtest"),
        "get_preset": ("factorvae_tpu.presets", "get_preset"),
    }
    if name in lazy:
        import importlib

        module, attr = lazy[name]
        return getattr(importlib.import_module(module), attr)
    raise AttributeError(f"module 'factorvae_tpu' has no attribute {name!r}")


__version__ = "0.1.0"

__all__ = [
    "Config",
    "DataConfig",
    "MeshConfig",
    "ModelConfig",
    "TrainConfig",
    "AlphaLayer",
    "BetaLayer",
    "FactorDecoder",
    "FactorEncoder",
    "FactorPredictor",
    "FactorVAE",
    "FactorVAEOutput",
    "FeatureExtractor",
]
