"""Named experiment presets for the benchmark configs in BASELINE.json.

The reference's deployed hyperparameters diverge from its CLI defaults
(SURVEY.md §5 "Config/flag system"): the score CSVs use K=20/48/60 with
H=K on 158 features (scores/readme.md), the notebook loads K=64/H=32/
M=100, and the CLI defaults to K=96/H=64/M=128. These presets pin the
five BASELINE.json configs plus the CLI-default flagship.

Every preset's `compute_dtype="bfloat16"` is the measured-best TPU
default (PERF.md). Since the mixed-precision path landed it no longer
means a whole-model cast: training resolves the dtype through
`TrainConfig.compute_dtype` (default: this model knob) into the
master-weight path — f32 params/optimizer state, one bf16 compute
cast, dynamic loss scaling (train/state.py; docs/precision.md) —
while scoring keeps the serving ladder's `serve_precision` choice.
"""

from __future__ import annotations

from factorvae_tpu.config import Config, DataConfig, ModelConfig, TrainConfig


def _csi300(num_factors: int, hidden: int, run: str) -> Config:
    return Config(
        model=ModelConfig(
            num_features=158, hidden_size=hidden, num_factors=num_factors,
            num_portfolios=128, seq_len=20,
            compute_dtype="bfloat16",
        ),
        data=DataConfig(dataset_path="./data/csi_data.pkl", seq_len=20),
        train=TrainConfig(run_name=run),
    )


PRESETS = {
    # reference CLI defaults (main.py:92-113)
    "flagship": _csi300(96, 64, "flagship"),
    # BASELINE.json configs 1-3: K in {20,48,60}, H=K (scores/readme.md)
    "csi300-k20": _csi300(20, 20, "free20"),
    "csi300-k48": _csi300(48, 48, "free48"),
    "csi300-k60": _csi300(60, 60, "free60"),
    # BASELINE.json config 4: CSI800 full cross-section (N ~= 800).
    # No fixed max_stocks: the old 1024 pad made 28% of every matmul
    # dead rows (SCALE_DEMO.json); the scale-aware pad policy
    # (plan.pad_target_policy) now pads 800 -> 800. Pass --max_stocks
    # (or a 'stock' mesh axis, which the policy folds in via its shard
    # argument) when even sharding needs a specific width.
    "csi800-k60": Config(
        model=ModelConfig(num_features=158, hidden_size=60, num_factors=60,
                          num_portfolios=128, seq_len=20,
                          compute_dtype="bfloat16"),
        data=DataConfig(dataset_path="./data/csi800_data.pkl", seq_len=20),
        train=TrainConfig(run_name="csi800_k60"),
    ),
    # BASELINE.json config 5: Alpha360 features, seq_len=60
    "alpha360-k60": Config(
        model=ModelConfig(num_features=360, hidden_size=60, num_factors=60,
                          num_portfolios=128, seq_len=60,
                          compute_dtype="bfloat16"),
        data=DataConfig(dataset_path="./data/csi_alpha360.pkl", seq_len=60),
        train=TrainConfig(run_name="alpha360_k60"),
    ),
}


def get_preset(name: str) -> Config:
    try:
        return PRESETS[name]
    except KeyError:
        raise KeyError(f"unknown preset {name!r}; choose from {sorted(PRESETS)}")
