"""graftlint — JAX-aware static + semantic analysis for this repo.

Two backends. The AST backend (JGL rules) checks what the source says:
no host sync inside jitted epoch bodies, donated buffers never read
after the donating call, every PRNG key consumed exactly once, jits
constructed once per config (not per call), hot-path array constructors
pinned to an explicit dtype so a bf16 plan is not silently f32. A stray
`.item()` or reused key costs the chip-day win or breaks seed
independence without failing a single test — so the invariants are
checked at the AST level instead, on every tier-1 run. The IR backend
(JIR rules, `analysis/ir.py`) checks what XLA actually compiled: it
abstractly lowers the repo's real jitted entry points (train/eval
epochs, scoring scans, serving rungs — never executing them) and walks
the jaxpr + post-SPMD HLO for claims the source only declares.

Rule catalog (docs/analysis.md has the long-form version):

- JGL001  host sync in traced code (float()/.item()/np.asarray/
          jax.device_get/block_until_ready under jit/scan/vmap), plus
          the per-element host-pull loop flavor outside traced code.
- JGL002  PRNG key reuse: a key consumed twice with no interleaving
          split/fold_in rebind.
- JGL003  jit-cache hazards: jax.jit constructed in a per-call scope
          (no lru_cache on the factory, not instance-cached), and
          unhashable literals passed at static_argnums positions.
- JGL004  donated-buffer read-after-donation.
- JGL005  dtype drift: array constructors without an explicit dtype in
          plan-governed hot paths.
- JGL006  bare print() in library modules (route through the
          MetricsLogger/timeline stream).
- JGL007  broad `except Exception` that swallows the error silently.
- JGL008  wall-clock time.time() measuring a duration (the Timeline
          contract is monotonic perf_counter).
- JGL009  whole-program only: shared mutable attribute/global written
          across the thread/main-line boundary without its owning lock.
- JGL010  whole-program only: async-signal-unsafe work (logging, I/O,
          lock acquisition) reachable from a signal handler.
- JGL011  whole-program only: daemon=True thread performing file
          writes with no join/flush barrier on any shutdown path.
- JGL012  blocking network call (urlopen/create_connection/requests/
          HTTPConnection) without a timeout, or a zero-argument
          Event/Condition `.wait()` that cannot notice a dead waker.
- JGL013  timeline_span_begin paired with timeline_span_end in the
          same function (the token API is cross-thread handoff only;
          same-function pairing either leaks the span on exceptions or
          hand-rolls the timeline_span context manager).
- JGL000  meta: unparseable file, a `graftlint: disable` suppression
          carrying no justification, or — in IR mode — a registry
          builder that raised / an unknown program name (the gate
          reports what it could NOT check instead of no-opping
          green). Never suppressible.

IR rules (run with `--ir`; anchored at the program's `@_program(...)`
declaration in analysis/ir.py, where suppressions also live):

- JIR001  compiled dtype discipline: any f64 in any program; on bf16
          programs, zero bf16 dots (wholesale dropped cast) or an f32
          share of dot FLOPs past the program's sanctioned budget.
- JIR002  donation effectiveness: every donate_argnums claim must
          appear as real input_output_alias entries in the compiled
          HLO — zero aliases is a silently dropped donation.
- JIR003  partition coverage: exactly one rule per declared leaf,
          no dead rules across the registry, and the epoch carry's
          output_shardings a fixed point of its input_shardings.
- JIR004  serving hazards: closed-over constants past the baked-bytes
          budget (weights compiled into the executable) and
          weak-typed inputs (a guaranteed second retrace).

Suppression syntax (same line, or a standalone comment on the line
above)::

    x = host_read(y)  # graftlint: disable=JGL001 one scalar per epoch

The justification text after the rule list is REQUIRED — a bare disable
is itself a finding.

CLI::

    python -m factorvae_tpu.analysis factorvae_tpu scripts --format human
    python -m factorvae_tpu.analysis --project          # whole-program
    python -m factorvae_tpu.analysis --ir               # compiled programs
    python -m factorvae_tpu.analysis --ir --programs train_epoch,serve_int8

`--project` builds ONE cross-module index (import-resolved call graph,
thread/signal/HTTP entry reachability, per-class guarded-attribute
inference — analysis/project.py) over every path, which enables the
concurrency rules JGL009-011 and lets jit/scan reachability follow
calls across module boundaries. Per-path mode is unchanged: each file
stands alone, and the project rules stay off.

The runtime complement is `analysis/sanitize.py`: a lock-order
recorder tier-1 drives over the Checkpointer/Timeline/metrics/registry
/chaos lock set, failing on held-while-acquiring cycles static
analysis cannot prove (tests/test_sanitize.py).

The AST engine itself is stdlib-only (ast + tokenize) and never
executes or imports the code under analysis, so the whole-repo pass
takes well under a second. (Reaching it through
`python -m factorvae_tpu.analysis` still imports the parent package —
and therefore jax/flax; in-process callers like the tier-1 gate pay
nothing extra.) The IR backend traces and AOT-compiles (but never
runs) the registered programs — a full `--ir` sweep costs tens of
seconds; where the watchdog already captured a program's HLO in this
process, the audit reuses it from `obs/compile.compiled_view` instead
of compiling a second time.
"""

from factorvae_tpu.analysis.engine import (
    Finding,
    analyze_paths,
    analyze_project,
    analyze_source,
    main,
)

__all__ = ["Finding", "analyze_paths", "analyze_project",
           "analyze_source", "main"]
