"""Semantic graftlint: jaxpr/HLO-level audit of the COMPILED programs.

The AST backend (engine.py / rules.py) guards the *source*; this second
backend guards what XLA actually builds. It abstractly lowers the
repo's real jitted programs — no execution, shapes only, reusing
`obs/compile.py`'s `abstractify`/`capture_compile` surface, and never
paying a second lower+compile where the watchdog already captured one
(`obs.compile.compiled_view`) — over a declared **program registry**
(`REGISTRY`: the serial/fleet/hyper train+eval epochs, the four
`eval/predict` scoring scans, the serve precision rungs), then walks
the jaxpr and the post-SPMD HLO to enforce four rules:

- **JIR001 — dtype discipline.** f64 anywhere in a program is a
  finding (nothing in this repo ever wants x64 compute). Inside a
  declared-bf16 leg, the compute-dominant ops (dot_general /
  conv_general_dilated) must run in bf16: f32 dots beyond the
  program's sanctioned master-weight boundary count (default 0) mean
  the bf16 cast silently re-promoted mid-graph — exactly the PR-16
  regression class docs/precision.md warns about.
- **JIR002 — donation effectiveness.** Every `donate_argnums` claim
  must appear as a real `input_output_alias` entry in the compiled
  HLO. XLA drops unusable donations with at most a warning; a dropped
  donation silently doubles the argument's residency. This turns the
  `bench.py --mixed` remat/donation observations into checked facts.
- **JIR003 — partition coverage.** Every leaf of a program's declared
  state trees must be matched by EXACTLY one partition-rule-table
  entry (parallel/partition.py; zero matches means `shard_tree` would
  raise in production, two means first-match-wins is hiding a rule),
  dead table entries are flagged (aggregated across the audited set —
  `loss_scale` only exists on mixed states), and the epoch-jit output
  sharding of the carried state must be a FIXED POINT of its input
  sharding — the PR-6 failure (GSPMD re-sharding an unpinned output
  leaf that then mismatches the next call's in_shardings) codified.
- **JIR004 — serving retrace/bloat hazards.** A serving program must
  not bake large constants into the executable (the panel belongs in
  the arguments, not the compile payload) and must not take weak-typed
  inputs (a Python scalar at the boundary re-traces against strongly
  typed callers).

Findings are ordinary `engine.Finding`s anchored at the registry
declaration in THIS file, so the existing suppression machinery
applies verbatim: a `# graftlint: disable=JIR00x justification`
comment on a program's `@_program(...)` declaration suppresses with a
recorded justification, and an unjustified disable is a JGL000 finding
exactly as in the AST backend. CLI: `python -m factorvae_tpu.analysis
--ir [--programs a,b] [--format human|json]`.

Registry programs are built at TINY synthetic shapes — the properties
audited (dtype legs, donation aliases, rule-table coverage, output
sharding fixed points, baked constants, weak types) are shape-
independent, and tiny shapes keep the tier-1 self-audit gate's
compiles cheap.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from factorvae_tpu.analysis.engine import Finding, apply_suppressions

__all__ = [
    "Program",
    "ProgramSpec",
    "REGISTRY",
    "alias_report",
    "analyze_programs",
    "audit_program",
    "donation_audit",
]

# Compute-dominant primitives for the JIR001 bf16-leg check: everything
# else (adds, selects, reductions, the f32 loss-scale/optimizer math)
# is boundary or elementwise work the mixed design keeps in f32.
_DOT_PRIMS = ("dot_general", "conv_general_dilated")


@dataclasses.dataclass
class Program:
    """One audited compiled program: the jitted callable, the abstract
    arguments of one real call, and the program's declared contracts
    (what the four JIR rules check the IR against)."""

    fn: Any
    args: tuple
    # declared compute leg: "bfloat16" arms the JIR001 dot-dtype check
    compute_dtype: str = "float32"
    # declared donation claims (mirrors the jit's donate_argnums)
    donate_argnums: Tuple[int, ...] = ()
    # (table_name, rule_table, abstract_tree) coverage declarations
    coverage: Tuple[Tuple[str, Sequence, Any], ...] = ()
    # carried-state fixed point: arg index -> output index (or None)
    carried_arg: Optional[int] = None
    carried_out: Optional[int] = None
    serving: bool = False
    const_bytes_limit: int = 1 << 20
    # JIR001 dominance budget for a bf16 leg: the fraction of total
    # dot/conv FLOPs allowed to run f32. 0.0 = pure-bf16 compute; the
    # real programs sanction their deliberately-f32 factor head (the
    # encoder/decoder/predictor carry NO dtype plumbing — tiny per-day
    # matrices stay f32 for numerics while the compute-dominant
    # extractor casts; docs/precision.md) with a minority share.
    sanctioned_f32_dot_frac: float = 0.0
    # watchdog name for compiled-view reuse; defaults to fn.name
    watch_name: str = ""


@dataclasses.dataclass(frozen=True)
class ProgramSpec:
    """Registry entry: a name, a zero-arg builder returning a
    `Program`, and the declaration line findings anchor to (so the
    engine's suppression comments attach to the declaration)."""

    name: str
    build: Callable[[], Program]
    line: int


REGISTRY: List[ProgramSpec] = []


def _program(name: str):
    """Register a builder under `name`; findings for the program anchor
    at the decorated function's declaration line in this file."""

    def deco(fn):
        REGISTRY.append(ProgramSpec(name, fn, fn.__code__.co_firstlineno))
        return fn

    return deco


# ---------------------------------------------------------------------------
# jaxpr / HLO walkers
# ---------------------------------------------------------------------------


def _subjaxprs(value):
    """Jaxprs nested inside one eqn-param value (scan/cond/pjit bodies
    arrive as ClosedJaxpr/Jaxpr, sometimes in tuples/lists)."""
    import jax

    core = jax.core
    if isinstance(value, core.ClosedJaxpr):
        yield value.jaxpr
    elif isinstance(value, core.Jaxpr):
        yield value
    elif isinstance(value, (tuple, list)):
        for v in value:
            yield from _subjaxprs(v)


def _iter_eqns(jaxpr):
    """Every eqn of `jaxpr` and (recursively) of every jaxpr nested in
    its eqn params — scan bodies, cond branches, inlined pjit calls."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _subjaxprs(v):
                yield from _iter_eqns(sub)


def _make_jaxpr(prog: Program):
    """Closed jaxpr of one abstract call — tracing only, no lowering,
    no compile. Raises on an unbuildable trace: the caller converts
    that into a loud JGL000 finding (a gate must never no-op green)."""
    import jax

    fn = getattr(prog.fn, "_fn", prog.fn)  # unwrap WatchedJit
    return jax.make_jaxpr(lambda *a: fn(*a))(*prog.args)


_ALIAS_BLOCK_RE = re.compile(r"input_output_alias=\{(.*?)\}[,\s]*entry",
                             re.DOTALL)
_ALIAS_ENTRY_RE = re.compile(
    r"\{[0-9, ]*\}:\s*\(([0-9]+),\s*\{[0-9, ]*\},\s*(?:may|must)-alias\)")


def _hlo_aliased_params(hlo_text: str) -> List[int]:
    """Entry-parameter numbers that appear in the compiled module's
    `input_output_alias` map (flat-argument numbering: jit parameters
    are the flattened leaves of the call's arguments, in order)."""
    m = _ALIAS_BLOCK_RE.search(hlo_text)
    if m is None:
        # alias map absent entirely (no donation survived, or a
        # text-format skew) — fall back to scanning the whole header
        m = re.search(r"input_output_alias=\{([^\n]*)\}", hlo_text)
        if m is None:
            return []
    return sorted({int(p) for p in _ALIAS_ENTRY_RE.findall(m.group(1))})


def _compiled_view(prog: Program) -> dict:
    """The program's compiled artifacts (post-SPMD HLO text + in/out
    shardings): the watchdog's stashed first-miss capture when one
    exists for this jit (no second lower+compile), a fresh
    `capture_compile(want_text=True)` replay otherwise."""
    from factorvae_tpu.obs import compile as compilelib

    name = prog.watch_name or str(getattr(prog.fn, "name", "") or "")
    if name:
        view = compilelib.compiled_view(name)
        if view is not None and view.get("hlo_text"):
            return view
    rec = compilelib.capture_compile(prog.fn, prog.args, want_text=True)
    return {"hlo_text": rec.get("hlo_text"),
            "input_shardings": rec.get("input_shardings"),
            "output_shardings": rec.get("output_shardings")}


# ---------------------------------------------------------------------------
# JIR001 — dtype discipline
# ---------------------------------------------------------------------------


def _dot_flops(eqn) -> float:
    """Rough FLOP weight of one dot/conv eqn: 2 x |out| x contraction.
    Only RELATIVE weight matters here (f32 share of the program's dot
    FLOPs), so conv window arithmetic is approximated by |out| alone."""
    import numpy as np

    out_aval = eqn.outvars[0].aval
    flops = 2.0 * float(np.prod(out_aval.shape))
    dn = eqn.params.get("dimension_numbers")
    if eqn.primitive.name == "dot_general" and dn is not None:
        (lhs_contract, _), _ = dn
        lhs = eqn.invars[0].aval
        for d in lhs_contract:
            flops *= lhs.shape[d]
    return flops


def _dtype_findings(spec: ProgramSpec, prog: Program, closed,
                    path: str) -> List[Finding]:
    import numpy as np

    f64_prims: List[str] = []
    dot_count: Dict[str, int] = {}
    dot_flops: Dict[str, float] = {}
    for eqn in _iter_eqns(closed.jaxpr):
        for var in eqn.outvars:
            aval = getattr(var, "aval", None)
            dt = getattr(aval, "dtype", None)
            if dt is not None and dt == np.float64 \
                    and len(f64_prims) < 8:
                f64_prims.append(eqn.primitive.name)
        if eqn.primitive.name in _DOT_PRIMS:
            dt = str(eqn.outvars[0].aval.dtype)
            dot_count[dt] = dot_count.get(dt, 0) + 1
            dot_flops[dt] = dot_flops.get(dt, 0.0) + _dot_flops(eqn)
    out: List[Finding] = []
    if f64_prims:
        out.append(Finding(
            "JIR001", path, spec.line,
            f"[{spec.name}] f64 compute in the traced program "
            f"(via {', '.join(sorted(set(f64_prims)))}) — nothing in "
            "this repo wants x64; a Python float or np.float64 leaked "
            "into the trace", entry_point=f"ir:{spec.name}"))
    if prog.compute_dtype == "bfloat16":
        total = sum(dot_flops.values())
        f32_frac = dot_flops.get("float32", 0.0) / total if total else 0.0
        bf16_dots = dot_count.get("bfloat16", 0)
        if bf16_dots == 0 and sum(dot_count.values()) > 0:
            out.append(Finding(
                "JIR001", path, spec.line,
                f"[{spec.name}] declared-bf16 leg contains no bf16 "
                f"dot/conv at all (dot dtypes: {dot_count}) — the "
                "compute cast never happened",
                entry_point=f"ir:{spec.name}"))
        elif f32_frac > prog.sanctioned_f32_dot_frac:
            out.append(Finding(
                "JIR001", path, spec.line,
                f"[{spec.name}] declared-bf16 leg runs {f32_frac:.0%} "
                "of its dot/conv FLOPs in f32 (sanctioned: "
                f"{prog.sanctioned_f32_dot_frac:.0%}; op counts: "
                f"{dot_count}) — the master-weight cast re-promoted "
                "to f32 mid-graph", entry_point=f"ir:{spec.name}"))
    return out


# ---------------------------------------------------------------------------
# JIR002 — donation effectiveness
# ---------------------------------------------------------------------------


def alias_report(view: dict, args: tuple,
                 donate_argnums: Sequence[int]) -> dict:
    """Per-donation alias verdict from a compiled view. JSON-ready —
    this is also the `bench.py --mixed` per-leg donation audit block.

    Flat-parameter attribution: jit flattens the call's argument
    pytrees into one parameter list in order, so argnum i owns the
    contiguous leaf range [offset(i), offset(i)+leaves(i))."""
    import jax

    hlo = view.get("hlo_text")
    if not hlo:
        return {"ok": False, "error": "compiled HLO text unavailable",
                "declared": sorted(int(i) for i in donate_argnums),
                "aliased_params": 0, "per_arg": []}
    aliased = _hlo_aliased_params(hlo)
    sizes = [len(jax.tree_util.tree_leaves(a)) for a in args]
    offsets = [0]
    for s in sizes:
        offsets.append(offsets[-1] + s)
    per_arg = []
    for i in sorted(int(i) for i in donate_argnums):
        if i >= len(sizes):
            per_arg.append({"argnum": i, "leaves": 0, "aliased": 0,
                            "verified": False})
            continue
        lo, hi = offsets[i], offsets[i + 1]
        hits = [p for p in aliased if lo <= p < hi]
        per_arg.append({"argnum": i, "leaves": sizes[i],
                        "aliased": len(hits),
                        "verified": bool(hits)})
    return {"ok": True, "declared": [a["argnum"] for a in per_arg],
            "aliased_params": len(aliased), "per_arg": per_arg}


def donation_audit(fn, args: tuple,
                   donate_argnums: Sequence[int]) -> dict:
    """One-call donation audit for external consumers (bench.py): the
    compiled view (stash-first) of `fn` at `args`, reduced to the
    JIR002 alias report."""
    prog = Program(fn=fn, args=tuple(args),
                   donate_argnums=tuple(donate_argnums))
    return alias_report(_compiled_view(prog), prog.args,
                        prog.donate_argnums)


def _donation_findings(spec: ProgramSpec, prog: Program, view: dict,
                       path: str) -> List[Finding]:
    if not prog.donate_argnums:
        return []
    rep = alias_report(view, prog.args, prog.donate_argnums)
    if not rep["ok"]:
        return [Finding(
            "JIR002", path, spec.line,
            f"[{spec.name}] donate_argnums={tuple(prog.donate_argnums)} "
            f"declared but the compiled HLO is unavailable "
            f"({rep['error']}) — the donation claim cannot be verified",
            entry_point=f"ir:{spec.name}")]
    out = []
    for arg in rep["per_arg"]:
        if not arg["verified"]:
            out.append(Finding(
                "JIR002", path, spec.line,
                f"[{spec.name}] donated argument {arg['argnum']} "
                f"({arg['leaves']} leaves) produced ZERO input-output "
                "aliases in the compiled HLO — XLA dropped the "
                "donation silently (shape/dtype mismatch with every "
                "output?); the buffer is resident twice",
                entry_point=f"ir:{spec.name}"))
    return out


# ---------------------------------------------------------------------------
# JIR003 — partition coverage + carried-state fixed point
# ---------------------------------------------------------------------------


def _leaf_names(tree) -> List[str]:
    from factorvae_tpu.parallel import partition

    names: List[str] = []
    partition.named_tree_map(
        lambda name, leaf: names.append(name) or leaf, tree)
    return names


def _coverage_findings(spec: ProgramSpec, prog: Program, path: str,
                       table_hits: Dict[str, Dict[str, int]],
                       ) -> List[Finding]:
    """Exactly-one-rule coverage per leaf. Also accumulates per-table
    pattern hit counts into `table_hits` for the end-of-run dead-rule
    aggregation (a pattern may be live only on SOME programs' trees —
    `loss_scale` exists only on mixed states)."""
    out: List[Finding] = []
    for table_name, table, tree in prog.coverage:
        hits = table_hits.setdefault(
            table_name, {pat: 0 for pat, _ in table})
        for pat, _ in table:
            hits.setdefault(pat, 0)
        for name in _leaf_names(tree):
            matched = [pat for pat, _ in table if re.search(pat, name)]
            for pat in matched:
                hits[pat] += 1
            if not matched:
                out.append(Finding(
                    "JIR003", path, spec.line,
                    f"[{spec.name}] state leaf '{name}' matches NO "
                    f"{table_name} entry — shard_tree would raise on "
                    "a real mesh; add a rule for it",
                    entry_point=f"ir:{spec.name}"))
            elif len(matched) > 1:
                out.append(Finding(
                    "JIR003", path, spec.line,
                    f"[{spec.name}] state leaf '{name}' matches "
                    f"{len(matched)} {table_name} entries "
                    f"({matched}) — first-match-wins is silently "
                    "shadowing the later rule(s)",
                    entry_point=f"ir:{spec.name}"))
    return out


def _dead_rule_findings(table_hits: Dict[str, Dict[str, int]],
                        path: str, line: int) -> List[Finding]:
    out = []
    for table_name in sorted(table_hits):
        for pat, count in table_hits[table_name].items():
            if count == 0:
                out.append(Finding(
                    "JIR003", path, line,
                    f"dead partition rule: {table_name} pattern "
                    f"{pat!r} matched zero leaves across every audited "
                    "program — delete it or register the program whose "
                    "state it covers", entry_point="ir:<registry>"))
    return out


def _sharding_leaves(tree) -> list:
    import jax

    return jax.tree_util.tree_leaves(
        tree, is_leaf=lambda x: hasattr(x, "is_equivalent_to"))


def _fixed_point_findings(spec: ProgramSpec, prog: Program, view: dict,
                          path: str) -> List[Finding]:
    """Compiled output sharding of the carried state must equal the
    carried argument's input sharding, leaf for leaf."""
    import jax

    if prog.carried_arg is None or prog.carried_out is None:
        return []
    in_sh = view.get("input_shardings")
    out_sh = view.get("output_shardings")
    if in_sh is None or out_sh is None:
        return [Finding(
            "JIR003", path, spec.line,
            f"[{spec.name}] carried-state fixed point declared but the "
            "compiled shardings are unavailable — the out_shardings "
            "pin cannot be verified", entry_point=f"ir:{spec.name}")]
    args_sh = in_sh[0] if isinstance(in_sh, tuple) and len(in_sh) == 2 \
        and isinstance(in_sh[1], dict) else in_sh
    carried_in = _sharding_leaves(args_sh[prog.carried_arg])
    out_tree = out_sh if prog.carried_out is None else (
        out_sh[prog.carried_out]
        if isinstance(out_sh, (tuple, list)) else out_sh)
    carried_out = _sharding_leaves(out_tree)
    if len(carried_in) != len(carried_out):
        return [Finding(
            "JIR003", path, spec.line,
            f"[{spec.name}] carried state has {len(carried_in)} input "
            f"sharding leaves but {len(carried_out)} output sharding "
            "leaves — output index/arg index declaration is wrong",
            entry_point=f"ir:{spec.name}")]
    avals = jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(lambda a: a, prog.args[prog.carried_arg]))
    out = []
    for i, (si, so) in enumerate(zip(carried_in, carried_out)):
        ndim = len(getattr(avals[i], "shape", ())) \
            if i < len(avals) else 0
        try:
            same = bool(si.is_equivalent_to(so, ndim))
        except (TypeError, ValueError):
            same = si == so
        if not same:
            out.append(Finding(
                "JIR003", path, spec.line,
                f"[{spec.name}] carried-state leaf {i}: output "
                f"sharding {so} != input sharding {si} — the epoch "
                "jit's out_shardings are NOT a fixed point of the "
                "carried state; the next call re-shards (the PR-6 "
                "failure)", entry_point=f"ir:{spec.name}"))
            if len(out) >= 4:  # one program, one storm — cap the noise
                break
    return out


# ---------------------------------------------------------------------------
# JIR004 — serving retrace/bloat hazards
# ---------------------------------------------------------------------------


def _all_consts(closed):
    """Constants of the closed jaxpr AND of every ClosedJaxpr nested in
    eqn params — a jit-closed-over array is hoisted into the inner
    pjit's closure, not the outer trace's."""
    import jax

    core = jax.core
    yield from closed.consts

    def walk(jaxpr):
        for eqn in jaxpr.eqns:
            for v in eqn.params.values():
                stack = [v]
                while stack:
                    item = stack.pop()
                    if isinstance(item, core.ClosedJaxpr):
                        yield from item.consts
                        yield from walk(item.jaxpr)
                    elif isinstance(item, core.Jaxpr):
                        yield from walk(item)
                    elif isinstance(item, (tuple, list)):
                        stack.extend(item)

    yield from walk(closed.jaxpr)


def _serving_findings(spec: ProgramSpec, prog: Program, closed,
                      path: str) -> List[Finding]:
    import numpy as np

    if not prog.serving:
        return []
    out: List[Finding] = []
    const_bytes = 0
    for c in _all_consts(closed):
        try:
            const_bytes += int(np.asarray(c).nbytes)
        except (TypeError, ValueError):
            continue
    if const_bytes > prog.const_bytes_limit:
        out.append(Finding(
            "JIR004", path, spec.line,
            f"[{spec.name}] serving program bakes "
            f"{const_bytes / 1e6:.1f} MB of constants into the "
            f"executable (limit {prog.const_bytes_limit / 1e6:.1f} MB) "
            "— a closed-over panel/param tree belongs in the jit "
            "arguments, not the compile payload",
            entry_point=f"ir:{spec.name}"))
    weak = [str(v.aval) for v in closed.jaxpr.invars
            if getattr(v.aval, "weak_type", False)]
    if weak:
        out.append(Finding(
            "JIR004", path, spec.line,
            f"[{spec.name}] serving program takes {len(weak)} "
            f"weak-typed input(s) ({weak[:4]}) — a Python scalar at "
            "the boundary retraces against strongly-typed callers; "
            "pass arrays with explicit dtypes",
            entry_point=f"ir:{spec.name}"))
    return out


# ---------------------------------------------------------------------------
# per-program audit + the analyze entry point
# ---------------------------------------------------------------------------


def audit_program(spec: ProgramSpec, prog: Program, path: str,
                  table_hits: Optional[Dict[str, Dict[str, int]]] = None,
                  ) -> List[Finding]:
    """All four JIR rules over one built program. Compiles only when a
    compiled artifact is actually needed (donation claims or a carried-
    state fixed point declared) and no watchdog capture is stashed."""
    findings: List[Finding] = []
    try:
        closed = _make_jaxpr(prog)
    except Exception as e:
        return [Finding(
            "JGL000", path, spec.line,
            f"[{spec.name}] program failed to trace — the IR gate "
            f"checks nothing here: {type(e).__name__}: {e}",
            entry_point=f"ir:{spec.name}")]
    findings.extend(_dtype_findings(spec, prog, closed, path))
    findings.extend(_serving_findings(spec, prog, closed, path))
    if table_hits is not None:
        findings.extend(
            _coverage_findings(spec, prog, path, table_hits))
    needs_compile = bool(prog.donate_argnums) or (
        prog.carried_arg is not None and prog.carried_out is not None)
    if needs_compile:
        view = _compiled_view(prog)
        findings.extend(_donation_findings(spec, prog, view, path))
        findings.extend(_fixed_point_findings(spec, prog, view, path))
    return findings


def _source_anchor() -> Tuple[str, str]:
    import inspect
    import sys

    path = inspect.getsourcefile(sys.modules[__name__]) or __file__
    with open(path, "r", encoding="utf-8") as fh:
        return path, fh.read()


def analyze_programs(names: Optional[Sequence[str]] = None,
                     registry: Optional[Sequence[ProgramSpec]] = None,
                     suppress: bool = True) -> List[Finding]:
    """Audit the program registry (or the `names` subset / an explicit
    fixture `registry`) and return findings with the engine's
    suppression comments applied over THIS file's source. The
    aggregated dead-rule check runs only over a full-registry audit
    (or any explicit registry): on a `names` subset a table entry can
    look dead merely because its program was filtered out."""
    specs = list(REGISTRY if registry is None else registry)
    if names is not None:
        wanted = set(names)
        unknown = wanted - {s.name for s in specs}
        specs = [s for s in specs if s.name in wanted]
    else:
        unknown = set()
    path, src = _source_anchor()
    findings: List[Finding] = []
    for n in sorted(unknown):
        findings.append(Finding(
            "JGL000", path, 1,
            f"unknown program {n!r} — the IR gate would check nothing "
            "here (known: "
            f"{', '.join(s.name for s in (registry or REGISTRY))})",
            entry_point=f"ir:{n}"))
    table_hits: Dict[str, Dict[str, int]] = {}
    for spec in specs:
        try:
            prog = spec.build()
        except Exception as e:
            findings.append(Finding(
                "JGL000", path, spec.line,
                f"[{spec.name}] program builder failed — the IR gate "
                f"checks nothing here: {type(e).__name__}: {e}",
                entry_point=f"ir:{spec.name}"))
            continue
        findings.extend(audit_program(spec, prog, path, table_hits))
    if names is None:
        findings.extend(_dead_rule_findings(
            table_hits, path, _program.__code__.co_firstlineno))
    if not suppress:
        return findings
    return apply_suppressions(src, ast.parse(src), path, findings)


# ---------------------------------------------------------------------------
# the program registry: every compiled program the repo ships
# ---------------------------------------------------------------------------
#
# Builders construct the REAL jits (Trainer/FleetTrainer/_score_*_fn —
# the exact watch_jit-wrapped programs production calls) over tiny
# synthetic panels, then hand audit_program abstract ShapeDtypeStruct
# arguments. Construction only: eval_shape for states, no train step,
# no scoring dispatch ever runs.


def _tiny_config(train_dtype: Optional[str] = None,
                 model_dtype: str = "float32", pallas: bool = False):
    from factorvae_tpu.config import (
        Config, DataConfig, ModelConfig, TrainConfig,
    )
    from factorvae_tpu.data import PanelDataset, synthetic_panel

    panel = synthetic_panel(num_days=16, num_instruments=5,
                            num_features=6, missing_prob=0.1, seed=0)
    ds = PanelDataset(panel, seq_len=4)
    cfg = Config(
        model=ModelConfig(num_features=6, hidden_size=8, num_factors=3,
                          num_portfolios=4, seq_len=4,
                          compute_dtype=model_dtype,
                          use_pallas_gru=pallas,
                          use_pallas_attention=pallas),
        data=DataConfig(seq_len=4, start_time=None,
                        fit_end_time=str(ds.dates[10].date()),
                        val_start_time=str(ds.dates[11].date()),
                        val_end_time=str(ds.dates[-1].date())),
        train=TrainConfig(num_epochs=1, lr=1e-3, seed=0,
                          save_dir="/tmp/graftlint_ir",
                          checkpoint_every=0,
                          compute_dtype=train_dtype),
    )
    return cfg, ds


def _abstract(tree):
    from factorvae_tpu.obs import compile as compilelib

    return compilelib.abstractify(tree)


def _train_epoch_program(train_dtype: Optional[str],
                         pallas: bool = False) -> Program:
    import jax

    from factorvae_tpu.parallel import partition
    from factorvae_tpu.train import Trainer
    from factorvae_tpu.utils.logging import MetricsLogger

    cfg, ds = _tiny_config(train_dtype=train_dtype, pallas=pallas)
    tr = Trainer(cfg, ds, logger=MetricsLogger(echo=False))
    state = jax.eval_shape(tr.init_state)
    args = (state, _abstract(tr._epoch_orders(0)),
            _abstract(tr.panel_args()))
    panel = {"values": ds.values, "last_valid": ds.last_valid,
             "next_valid": ds.next_valid}
    return Program(
        fn=tr._train_epoch_jit, args=args,
        compute_dtype=tr._train_dtype, donate_argnums=(0,),
        coverage=(
            ("TRAIN_STATE_RULES", partition.TRAIN_STATE_RULES, state),
            ("PANEL_RULES", partition.PANEL_RULES, _abstract(panel)),
        ),
        carried_arg=0, carried_out=0)


@_program("train_epoch")
def _build_train_epoch() -> Program:
    """Serial f32 train epoch: state donation + TRAIN_STATE_RULES/
    PANEL_RULES coverage + carried-state fixed point."""
    return _train_epoch_program(train_dtype=None)


@_program("train_epoch_bf16")
def _build_train_epoch_bf16() -> Program:
    """Mixed-precision train epoch (PR 16): the declared-bf16 leg the
    JIR001 dot-dtype walk guards. The factor head (encoder/decoder/
    predictor — tiny per-day matrices, no dtype plumbing by design) is
    sanctioned to stay f32 as a MINORITY of dot FLOPs; at this gate's
    tiny audit shapes the head is ~40% of dot FLOPs (it shrinks with
    real model sizes), so the 50% budget still trips on the real
    failure: the extractor cast silently undone (share -> ~100%)."""
    prog = _train_epoch_program(train_dtype="bfloat16")
    prog.sanctioned_f32_dot_frac = 0.5
    return prog


@_program("train_epoch_pallas")
def _build_train_epoch_pallas() -> Program:
    """Plan-raced kernel leg (PR 19): the train epoch with BOTH fused
    kernels engaged (use_pallas_gru + use_pallas_attention), the exact
    jit a `kernels` plan block with pallas winners ships. Audited so
    the custom-VJP wiring cannot silently break the state-donation
    aliasing or the dtype trace the f32 program pins. On CPU the
    kernels lower through interpret mode — the compiled artifact
    differs from the Mosaic one, but the jaxpr-level contracts
    (donation, rule coverage, carried fixed point) are the same."""
    return _train_epoch_program(train_dtype=None, pallas=True)


@_program("eval_epoch")
def _build_eval_epoch() -> Program:
    import jax

    from factorvae_tpu.train import Trainer
    from factorvae_tpu.utils.logging import MetricsLogger

    cfg, ds = _tiny_config()
    tr = Trainer(cfg, ds, logger=MetricsLogger(echo=False))
    params = jax.eval_shape(tr.init_state).params
    key = _abstract(jax.random.PRNGKey(1))
    order = tr._val_order()
    args = (params, _abstract(order), key, _abstract(tr.panel_args()))
    return Program(fn=tr._eval_epoch_jit, args=args,
                   compute_dtype=tr._train_dtype)


def _fleet(num_seeds: int = 2, hyper: bool = False):
    from factorvae_tpu.train import FleetTrainer
    from factorvae_tpu.utils.logging import MetricsLogger

    if hyper:
        # bf16 hyper lanes: exercises the runtime-scalar trace AND a
        # MIXED fleet state, so `loss_scale`/`good_steps` (None leaves
        # on f32 states) register as live FLEET_STATE_RULES matches in
        # the JIR003 dead-rule aggregation. Per-lane save_dir: lane
        # checkpoint paths must not collide (validate_lane_configs).
        cfg, ds = _tiny_config(train_dtype="bfloat16")
        lanes = []
        for i, lr in enumerate((1e-3, 2e-3)):
            lanes.append(dataclasses.replace(
                cfg, train=dataclasses.replace(
                    cfg.train, lr=lr,
                    save_dir=f"{cfg.train.save_dir}/lane{i}")))
        return FleetTrainer(cfg, ds, lane_configs=lanes,
                            logger=MetricsLogger(echo=False)), ds
    cfg, ds = _tiny_config()
    return FleetTrainer(cfg, ds, seeds=list(range(num_seeds)),
                        logger=MetricsLogger(echo=False)), ds


def _fleet_train_program(hyper: bool) -> Program:
    import jax

    from factorvae_tpu.parallel import partition

    ft, _ = _fleet(hyper=hyper)
    state = jax.eval_shape(ft.init_fleet_state)
    args = [state, _abstract(ft._epoch_orders(0)),
            _abstract(ft.panel_args())]
    args.extend(_abstract(ft._hp_args()))
    return Program(
        fn=ft._train_epoch_jit, args=tuple(args),
        compute_dtype=ft._train_dtype, donate_argnums=(0,),
        coverage=(("FLEET_STATE_RULES", partition.FLEET_STATE_RULES,
                   state),),
        carried_arg=0, carried_out=0)


@_program("fleet_train_epoch")
def _build_fleet_train_epoch() -> Program:
    """Stacked 2-seed fleet train epoch: FLEET_STATE_RULES coverage +
    stacked-state donation + fixed point."""
    return _fleet_train_program(hyper=False)


@_program("hyper_train_epoch")
def _build_hyper_train_epoch() -> Program:
    """bf16 hyper fleet (per-lane lr as runtime scalars, PR 12): the
    seed fleet's contracts over the scalar-threaded MIXED trace — the
    one registry program whose fleet state carries loss_scale/
    good_steps leaves. f32-head sanction as in train_epoch_bf16."""
    prog = _fleet_train_program(hyper=True)
    prog.sanctioned_f32_dot_frac = 0.5
    return prog


@_program("fleet_eval_epoch")
def _build_fleet_eval_epoch() -> Program:
    import jax

    ft, _ = _fleet(hyper=False)
    state = jax.eval_shape(ft.init_fleet_state)
    keys = _abstract(ft._eval_keys(0))
    args = (state.params, _abstract(ft._val_order()), keys,
            _abstract(ft.panel_args()))
    return Program(fn=ft._eval_epoch_jit, args=args,
                   compute_dtype=ft._train_dtype)


def _score_inputs(ds, model_cfg, stacked: bool = False,
                  scan: bool = False):
    """Abstract (params, panel..., day_idx, key(s)) for the scoring
    programs, mirroring eval/predict's real call shapes."""
    import jax
    import numpy as np

    from factorvae_tpu.eval.predict import _scan_inputs
    from factorvae_tpu.train import Trainer
    from factorvae_tpu.utils.logging import MetricsLogger

    cfg, _ = _tiny_config(model_dtype=model_cfg.compute_dtype)
    cfg = dataclasses.replace(cfg, model=model_cfg)
    params = jax.eval_shape(
        Trainer(cfg, ds, logger=MetricsLogger(echo=False)).init_state
    ).params
    if stacked:
        params = jax.tree_util.tree_map(
            lambda s: jax.ShapeDtypeStruct((2,) + tuple(s.shape),
                                           s.dtype), params)
    days = np.arange(len(ds.dates), dtype=np.int32)
    base = jax.random.PRNGKey(0)
    if scan:
        day_idx, keys = _scan_inputs(days, 4, base, False)
        tail = (_abstract(day_idx), _abstract(keys))
    else:
        tail = (_abstract(jax.numpy.asarray(days[:4])), _abstract(base))
    return (params, _abstract(ds.values), _abstract(ds.last_valid),
            _abstract(ds.next_valid)) + tail


def _scoring_program(fleet: bool, scan: bool,
                     pallas: bool = False) -> Program:
    import jax

    from factorvae_tpu.eval import predict

    cfg, ds = _tiny_config(pallas=pallas)
    factory = {
        (False, False): predict._score_chunk_fn,
        (True, False): predict._score_chunk_fleet_fn,
        (False, True): predict._score_scan_fn,
        (True, True): predict._score_scan_fleet_fn,
    }[(fleet, scan)]
    fn = factory(cfg.model, cfg.data.seq_len, None, False)
    args = _score_inputs(ds, cfg.model, stacked=fleet, scan=scan)
    # score_scan mirrors the factory's backend-conditional donation
    # (day_idx/keys buffers; a no-op where aliasing is unsupported)
    donate = (4, 5) if (scan and not fleet
                        and jax.default_backend() != "cpu") else ()
    return Program(fn=fn, args=args,
                   compute_dtype=cfg.model.compute_dtype,
                   donate_argnums=donate)


@_program("score_chunk")
def _build_score_chunk() -> Program:
    return _scoring_program(fleet=False, scan=False)


@_program("score_chunk_pallas")
def _build_score_chunk_pallas() -> Program:
    """Kernel-leg scoring twin of train_epoch_pallas (PR 19): the
    chunked scorer with both fused kernels engaged. No donation by
    design — and in particular the eval/score keys stay un-donated
    (the measured PR 19 verdict: XLA drops a (2,) uint32 key donation
    against f32 outputs, see train/trainer.py)."""
    return _scoring_program(fleet=False, scan=False, pallas=True)


@_program("score_chunk_fleet")
def _build_score_chunk_fleet() -> Program:
    return _scoring_program(fleet=True, scan=False)


@_program("score_scan")
def _build_score_scan() -> Program:
    return _scoring_program(fleet=False, scan=True)


@_program("score_scan_fleet")
def _build_score_scan_fleet() -> Program:
    return _scoring_program(fleet=True, scan=True)


def _serve_rung_program(precision: str) -> Program:
    import jax

    from factorvae_tpu.eval import predict
    from factorvae_tpu.serve.registry import precision_config

    cfg, ds = _tiny_config()
    rung = precision_config(cfg, precision)
    int8 = precision == "int8"
    fn = predict._score_chunk_fn(rung.model, rung.data.seq_len, None,
                                 int8)
    args = _score_inputs(ds, rung.model)
    if int8:
        from factorvae_tpu.ops.quant import quantize_params

        args = (jax.eval_shape(quantize_params, args[0]),) + args[1:]
    return Program(fn=fn, args=args,
                   compute_dtype=rung.model.compute_dtype,
                   serving=True)


@_program("serve_float32")
def _build_serve_float32() -> Program:
    """Serving ladder rung (serve/registry.PRECISIONS): JIR004 baked-
    constant/weak-type checks armed on the daemon's scoring program."""
    return _serve_rung_program("float32")


@_program("serve_bfloat16")
def _build_serve_bfloat16() -> Program:
    """bf16 rung: f32 factor head sanctioned as a minority of dot
    FLOPs, as in train_epoch_bf16 (same model, forward only)."""
    prog = _serve_rung_program("bfloat16")
    prog.sanctioned_f32_dot_frac = 0.5
    return prog


@_program("serve_int8")
def _build_serve_int8() -> Program:
    return _serve_rung_program("int8")
