"""graftlint engine: module model, traced-reachability, suppressions, CLI.

The unit of analysis is one module. For each file the engine builds a
`ModuleModel`: the parsed AST, the import-alias table (`jnp` ->
`jax.numpy`, ...), every function (nested defs and lambdas included), a
name-based call graph, the set of functions reachable from a trace
context (jit / scan / vmap / grad bodies), and the jit wrappers
constructed in the module together with their `donate_argnums` /
`static_argnums`. The rules in rules.py consume that model and emit
`Finding`s; the engine then applies the suppression comments and decides
the exit code.

Name resolution is deliberately module-local and name-based: a call to
`chunk_scores(...)` links to ANY local `def chunk_scores` — including a
closure returned by a factory — because that is exactly the idiom the
hot paths use (eval/predict.py's lru_cached jit factories). The
over-approximation this buys (same-named unrelated functions link too)
is the standard lint trade-off; suppressions carry the rare false
positive.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import io
import json
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

# ---------------------------------------------------------------------------
# canonical JAX surface the rules key on

# wrapper -> positions of the function-valued argument(s) that get traced
TRACED_FN_ARGS: Dict[str, Tuple[int, ...]] = {
    "jax.jit": (0,),
    "jax.pjit": (0,),
    "jax.experimental.pjit.pjit": (0,),
    "jax.vmap": (0,),
    "jax.pmap": (0,),
    "jax.grad": (0,),
    "jax.value_and_grad": (0,),
    "jax.checkpoint": (0,),
    "jax.remat": (0,),
    "jax.lax.scan": (0,),
    "jax.lax.map": (0,),
    "jax.lax.cond": (1, 2),
    "jax.lax.switch": (1,),
    "jax.lax.while_loop": (0, 1),
    "jax.lax.fori_loop": (2,),
    "jax.custom_vjp": (0,),
    "jax.custom_jvp": (0,),
}

# the subset that actually COMPILES (JGL003 cares about these only)
JIT_WRAPPERS = {"jax.jit", "jax.pjit", "jax.experimental.pjit.pjit"}

# One-level instrumentation wrappers that pass the jit through
# TRANSPARENTLY — same calling convention, same argument positions
# (obs/watchdog.py). These are the ONLY outer calls the donation/static
# tables and JGL003's instance-cache exemption look through: an
# arbitrary enclosing call (a functools.partial re-mapping argument
# positions, or an immediate invocation `jax.jit(f)(x)`) must NOT
# inherit the jit's config or its caching exemption.
INSTRUMENTATION_WRAPPERS = {"watch_jit"}

# key-deriving calls: reading a key here is sanctioned, not consumption
KEY_DERIVERS = {
    "jax.random.split",
    "jax.random.fold_in",
    "jax.random.clone",
}
# key-producing calls: assignment targets become tracked keys
KEY_PRODUCERS = KEY_DERIVERS | {
    "jax.random.PRNGKey",
    "jax.random.key",
    "jax.random.wrap_key_data",
}

CACHE_DECORATORS = {
    "functools.lru_cache",
    "functools.cache",
}

_SUPPRESS_RE = re.compile(
    r"graftlint:\s*disable=([A-Za-z0-9_,]+)[ \t]*(.*)$"
)
_HOT_PRAGMA_RE = re.compile(r"graftlint:\s*hot-path\b")

# plan-governed hot paths for JGL005 (see docs/analysis.md): modules whose
# compute dtype the execution planner owns. A module outside these opts in
# with a `# graftlint: hot-path` pragma anywhere in the file.
HOT_PATH_PATTERNS = (
    "factorvae_tpu/train/",
    "factorvae_tpu/eval/predict",
    "factorvae_tpu/ops/",
    "factorvae_tpu/data/windows",
)


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    message: str
    suppressed: bool = False
    justification: str = ""
    # Whole-program fields (ISSUE 11): set by the concurrency rules in
    # --project mode. `thread_reachable` marks a finding whose flagged
    # scope runs off the main thread (thread target, executor submit,
    # HTTP handler, signal handler); `entry_point` names the entry the
    # reachability walk reached it through. Module-local findings keep
    # the defaults, so the JSON schema is additive, never breaking.
    thread_reachable: bool = False
    entry_point: str = ""

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Suppression:
    line: int          # code line the suppression applies to
    rules: Set[str]
    justification: str
    comment_line: int  # where the comment physically lives


@dataclasses.dataclass
class FuncInfo:
    node: ast.AST                      # FunctionDef | AsyncFunctionDef | Lambda
    name: str                          # "<lambda>" for lambdas
    qualname: str
    parent: Optional["FuncInfo"]
    traced: bool = False

    def decorator_list(self) -> list:
        return getattr(self.node, "decorator_list", [])


class ModuleModel:
    """Everything the rules need to know about one parsed module."""

    def __init__(self, path: str, src: str, tree: ast.Module,
                 hot_path: Optional[bool] = None):
        self.path = path
        self.src = src
        self.tree = tree
        self.aliases = _collect_aliases(tree)
        self.functions: List[FuncInfo] = []
        self._func_by_node: Dict[ast.AST, FuncInfo] = {}
        self._funcs_by_name: Dict[str, List[FuncInfo]] = {}
        self._parents: Dict[ast.AST, ast.AST] = {}
        self._collect_functions()
        # donators/static: callable name -> argument positions
        self.donators: Dict[str, Tuple[int, ...]] = {}
        self.static_args: Dict[str, Tuple[int, ...]] = {}
        self._collect_jit_wrappers()
        self._mark_traced()
        norm = path.replace(os.sep, "/")
        if hot_path is None:
            hot_path = any(p in norm for p in HOT_PATH_PATTERNS) or bool(
                _HOT_PRAGMA_RE.search(src)
            )
        self.hot_path = hot_path

    # -- structure ---------------------------------------------------------

    def resolve(self, expr: ast.AST) -> Optional[str]:
        """Dotted name of an expression through the import-alias table
        (`jnp.zeros` -> "jax.numpy.zeros"), or None for non-name exprs."""
        parts = []
        while isinstance(expr, ast.Attribute):
            parts.append(expr.attr)
            expr = expr.value
        if not isinstance(expr, ast.Name):
            return None
        parts.append(expr.id)
        parts.reverse()
        head = self.aliases.get(parts[0], parts[0])
        return ".".join([head] + parts[1:])

    def funcs_named(self, name: str) -> List[FuncInfo]:
        return self._funcs_by_name.get(name, [])

    def enclosing_function(self, node: ast.AST) -> Optional[FuncInfo]:
        cur = self._parents.get(node)
        while cur is not None:
            info = self._func_by_node.get(cur)
            if info is not None:
                return info
            cur = self._parents.get(cur)
        return None

    def func_of(self, node: ast.AST) -> Optional[FuncInfo]:
        return self._func_by_node.get(node)

    def _collect_functions(self) -> None:
        def visit(node, parent_info, prefix):
            for child in ast.iter_child_nodes(node):
                self._parents[child] = node
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qn = f"{prefix}{child.name}"
                    info = FuncInfo(child, child.name, qn, parent_info)
                    self._register(info)
                    visit(child, info, qn + ".")
                elif isinstance(child, ast.Lambda):
                    qn = f"{prefix}<lambda@{child.lineno}>"
                    info = FuncInfo(child, "<lambda>", qn, parent_info)
                    self._register(info)
                    visit(child, info, qn + ".")
                elif isinstance(child, ast.ClassDef):
                    visit(child, parent_info, f"{prefix}{child.name}.")
                else:
                    visit(child, parent_info, prefix)

        visit(self.tree, None, "")

    def _register(self, info: FuncInfo) -> None:
        self.functions.append(info)
        self._func_by_node[info.node] = info
        self._funcs_by_name.setdefault(info.name, []).append(info)

    # -- jit wrappers (donation / static args) -----------------------------

    def _jit_call_info(self, call: ast.Call) -> Optional[dict]:
        """If `call` is jax.jit(...)/pjit(...), its keyword config."""
        if self.resolve(call.func) not in JIT_WRAPPERS:
            return None
        out = {"donate": (), "static": ()}
        for kw in call.keywords:
            if kw.arg in ("donate_argnums", "static_argnums"):
                key = "donate" if kw.arg == "donate_argnums" else "static"
                out[key] = _int_tuple(kw.value)
        return out

    def _unwrap_jit_call(self, call: ast.Call) -> Optional[ast.Call]:
        """`call` itself when it is jax.jit(...), else the jit call
        passed to a KNOWN transparent instrumentation wrapper —
        `self._f = watch_jit(jax.jit(g, donate_argnums=(0,)), "g")`
        (obs/watchdog.py) must keep its donation/static tracking, or
        wrapping a jit would silently blind JGL004. Only
        INSTRUMENTATION_WRAPPERS qualify: a functools.partial (or any
        other call) around a jit re-maps argument positions, so
        inheriting the jit's argnums there would mis-attribute
        donations."""
        if self.resolve(call.func) in JIT_WRAPPERS:
            return call
        if _terminal_name(call.func) not in INSTRUMENTATION_WRAPPERS:
            return None
        for a in call.args:
            if isinstance(a, ast.Call) and self.resolve(a.func) in JIT_WRAPPERS:
                return a
        return None

    def _collect_jit_wrappers(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                jit_call = self._unwrap_jit_call(node.value)
                info = self._jit_call_info(jit_call) \
                    if jit_call is not None else None
                if info is None:
                    continue
                for tgt in node.targets:
                    name = _target_callable_name(tgt)
                    if name is None:
                        continue
                    if info["donate"]:
                        self.donators[name] = info["donate"]
                    if info["static"]:
                        self.static_args[name] = info["static"]
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    info = None
                    if isinstance(dec, ast.Call):
                        if self.resolve(dec.func) in (
                            "functools.partial", "partial"
                        ) and dec.args and self.resolve(
                            dec.args[0]
                        ) in JIT_WRAPPERS:
                            info = {"donate": (), "static": ()}
                            for kw in dec.keywords:
                                if kw.arg == "donate_argnums":
                                    info["donate"] = _int_tuple(kw.value)
                                if kw.arg == "static_argnums":
                                    info["static"] = _int_tuple(kw.value)
                        else:
                            jinfo = self._jit_call_info(dec)
                            if jinfo is not None:
                                info = jinfo
                    if info is None:
                        continue
                    if info["donate"]:
                        self.donators[node.name] = info["donate"]
                    if info["static"]:
                        self.static_args[node.name] = info["static"]

    # -- traced reachability ----------------------------------------------

    def _decorated_traced(self, fn: FuncInfo) -> bool:
        for dec in fn.decorator_list():
            name = self.resolve(dec)
            if name in TRACED_FN_ARGS:
                return True
            if isinstance(dec, ast.Call):
                if self.resolve(dec.func) in TRACED_FN_ARGS:
                    return True
                if self.resolve(dec.func) in ("functools.partial", "partial") \
                        and dec.args \
                        and self.resolve(dec.args[0]) in TRACED_FN_ARGS:
                    return True
        return False

    def _mark_traced(self) -> None:
        seeds: Set[ast.AST] = set()
        for fn in self.functions:
            if not isinstance(fn.node, ast.Lambda) and self._decorated_traced(fn):
                seeds.add(fn.node)
        # function-valued args of trace wrappers, anywhere in the module
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            wrapper = self.resolve(node.func)
            positions = TRACED_FN_ARGS.get(wrapper or "")
            if not positions:
                continue
            for pos in positions:
                if pos >= len(node.args):
                    continue
                arg = node.args[pos]
                if isinstance(arg, ast.Lambda):
                    seeds.add(arg)
                else:
                    name = _terminal_name(arg)
                    if name:
                        for f in self.funcs_named(name):
                            seeds.add(f.node)

        for fn in self.functions:
            if fn.node in seeds:
                fn.traced = True

        self._propagate_traced()

    def _propagate_traced(self) -> None:
        # propagate: through local calls by name + nested defs
        changed = True
        while changed:
            changed = False
            for fn in self.functions:
                if not fn.traced:
                    # nested def inside a traced function runs under trace
                    if fn.parent is not None and fn.parent.traced:
                        fn.traced = True
                        changed = True
                    continue
                for call in _local_nodes(fn.node, ast.Call):
                    name = _terminal_name(call.func)
                    if not name:
                        continue
                    for callee in self.funcs_named(name):
                        if not callee.traced:
                            callee.traced = True
                            changed = True

    def seed_traced(self, names: Iterable[str]) -> bool:
        """Mark the named functions traced and re-propagate. The
        whole-program index (analysis/project.py) calls this when a
        traced function in ANOTHER module calls into this one through
        an import-resolved edge — reachability follows calls across
        module boundaries instead of stopping at them. Returns whether
        anything new was marked."""
        changed = False
        for name in names:
            for f in self.funcs_named(name):
                if not f.traced:
                    f.traced = True
                    changed = True
        if changed:
            self._propagate_traced()
        return changed

    def traced_entry_names(self) -> Set[str]:
        """Names whose call returns device values fresh off a compiled
        program: traced defs + names bound to jax.jit wrappers."""
        out = {f.name for f in self.functions
               if f.traced and f.name != "<lambda>"}
        out.update(self.donators)
        out.update(self.static_args)
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                if self._unwrap_jit_call(node.value) is not None:
                    for tgt in node.targets:
                        name = _target_callable_name(tgt)
                        if name:
                            out.add(name.split(".")[-1])
        return out


# ---------------------------------------------------------------------------
# small AST helpers shared with rules.py


def _collect_aliases(tree: ast.Module) -> Dict[str, str]:
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
            for a in node.names:
                if a.name == "*":
                    continue
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def _terminal_name(expr: ast.AST) -> Optional[str]:
    """`foo` -> "foo"; `self.fns.foo` -> "foo" (the name-match key)."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


def _target_callable_name(tgt: ast.AST) -> Optional[str]:
    """Assignment-target key for the donator table: a plain name, or
    `self.x` recorded as "self.x" (matched against self-attr call sites
    anywhere in the module — methods of one class in practice)."""
    if isinstance(tgt, ast.Name):
        return tgt.id
    if isinstance(tgt, ast.Attribute) and isinstance(tgt.value, ast.Name) \
            and tgt.value.id == "self":
        return f"self.{tgt.attr}"
    return None


def _int_tuple(expr: ast.AST) -> Tuple[int, ...]:
    """Literal int / tuple-of-int value of an AST node, else ()."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, int):
        return (expr.value,)
    if isinstance(expr, (ast.Tuple, ast.List)):
        out = []
        for el in expr.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, int):
                out.append(el.value)
            else:
                return ()
        return tuple(out)
    return ()


def _local_nodes(fn_node: ast.AST, *types) -> Iterable[ast.AST]:
    """Walk a function body WITHOUT descending into nested def/lambda
    (those are separate FuncInfos and get their own pass)."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        if not types or isinstance(node, tuple(types)):
            yield node
        stack.extend(ast.iter_child_nodes(node))


# ---------------------------------------------------------------------------
# suppressions


def _parse_suppressions(src: str) -> List[Suppression]:
    """All `# graftlint: disable=...` comments. A comment on a code line
    applies to that line; a standalone comment line applies to the next
    line that carries code. The caller turns empty justifications into
    JGL000 findings."""
    lines = src.splitlines()
    sups: List[Suppression] = []
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(src).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return sups
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        lineno = tok.start[0]
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        justification = m.group(2).strip().lstrip("-— ").strip()
        standalone = lines[lineno - 1][: tok.start[1]].strip() == ""
        target = lineno
        if standalone:
            for nxt in range(lineno, len(lines)):
                stripped = lines[nxt].strip()
                if stripped and not stripped.startswith("#"):
                    target = nxt + 1
                    break
        sups.append(Suppression(target, rules, justification, lineno))
    return sups


# ---------------------------------------------------------------------------
# driver


def _innermost_stmt_starts(tree: ast.Module) -> Dict[int, int]:
    """line -> first line of the INNERMOST statement spanning it (so a
    suppression on any physical line of a wrapped statement matches
    findings anchored to any other line of the same statement, without
    letting a big compound statement — a whole function body — swallow
    suppressions meant for one inner statement)."""
    best: Dict[int, Tuple[int, int]] = {}  # line -> (span_len, start)
    for node in ast.walk(tree):
        if not isinstance(node, ast.stmt):
            continue
        # decorator lines belong to the decorated statement: findings on
        # a decorated def anchor at the `def` line, but the natural
        # suppression placement is on the decorator
        first = node.lineno
        for dec in getattr(node, "decorator_list", []):
            first = min(first, dec.lineno)
        end = getattr(node, "end_lineno", None) or node.lineno
        span = (end - first, node.lineno)
        for ln in range(first, end + 1):
            if ln not in best or span < best[ln]:
                best[ln] = span
    return {ln: start for ln, (_, start) in best.items()}


def apply_suppressions(src: str, tree: ast.Module, path: str,
                       findings: List[Finding]) -> List[Finding]:
    """Apply the file's `graftlint: disable` comments to `findings`
    (marking covered ones suppressed) and append the JGL000 meta
    findings for unjustified suppressions. Shared by the module-local
    pass (analyze_source) and the whole-program pass (analyze_project),
    so suppression semantics are identical in both modes."""
    sups = _parse_suppressions(src)
    meta: List[Finding] = []
    for s in sups:
        if not s.justification:
            meta.append(Finding(
                "JGL000", path, s.comment_line,
                "graftlint suppression without a justification — say WHY "
                "the rule does not apply here",
            ))

    # A suppression covers a finding on the same physical line OR on the
    # same (innermost) multi-line statement: with wrapped calls the
    # finding anchors at the statement's first line while the trailing
    # comment physically sits on the last — both must match.
    stmt_of = _innermost_stmt_starts(tree)

    def covers(s: Suppression, f: Finding) -> bool:
        if not s.justification or not (f.rule in s.rules or "all" in s.rules):
            return False
        if s.line == f.line:
            return True
        s_stmt = stmt_of.get(s.line)
        return s_stmt is not None and s_stmt == stmt_of.get(f.line)

    out: List[Finding] = []
    for f in findings:
        sup = next((s for s in sups if covers(s, f)), None)
        if sup is not None:
            out.append(dataclasses.replace(
                f, suppressed=True, justification=sup.justification))
        else:
            out.append(f)
    out.extend(meta)
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


def run_module_rules(model: ModuleModel) -> List[Finding]:
    """Every module-local rule over one built model (no suppression
    application — the caller owns that so project mode can merge
    module-local and whole-program findings first)."""
    from factorvae_tpu.analysis import rules as _rules

    findings: List[Finding] = []
    for rule_fn in _rules.ALL_RULES:
        findings.extend(rule_fn(model))
    return findings


def analyze_source(src: str, path: str = "<string>",
                   hot_path: Optional[bool] = None) -> List[Finding]:
    """Run every rule over one module's source. Findings covered by a
    justified suppression come back with suppressed=True; an unjustified
    suppression is itself a JGL000 finding."""
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding("JGL000", path, e.lineno or 1,
                        f"unparseable file: {e.msg}")]
    model = ModuleModel(path, src, tree, hot_path=hot_path)
    return apply_suppressions(src, tree, path, run_module_rules(model))


def _walk_py_files(root_dir: str) -> Iterable[str]:
    for root, dirs, files in os.walk(root_dir):
        dirs[:] = sorted(
            d for d in dirs
            if d != "__pycache__" and not d.startswith(".")
        )
        for name in sorted(files):
            if name.endswith(".py"):
                yield os.path.join(root, name)


def collect_sources(paths: Sequence[str]
                    ) -> Tuple[List[Tuple[str, Optional[str], str]],
                               List[Finding]]:
    """Resolve CLI paths into [(file_path, package_root_or_None, src)]
    plus the JGL000 findings for anything missing/unreadable — a typo'd
    path must fail the gate loudly, never turn it into a green no-op.
    `package_root` is the directory argument a file was found under
    (the whole-program index derives dotted module names from it);
    files passed directly carry None and index as standalone modules."""
    out: List[Tuple[str, Optional[str], str]] = []
    findings: List[Finding] = []
    for p in paths:
        if os.path.isfile(p):
            if not p.endswith(".py"):
                findings.append(Finding(
                    "JGL000", p, 1, "not a Python file — nothing analyzed"))
                continue
            files = [(p, None)]
        elif os.path.isdir(p):
            files = [(f, p) for f in _walk_py_files(p)]
            if not files:
                findings.append(Finding(
                    "JGL000", p, 1,
                    "no Python files under this path — the gate would "
                    "check nothing here"))
                continue
        else:
            findings.append(Finding(
                "JGL000", p, 1,
                "path does not exist — a typo here would silently turn "
                "the lint gate into a no-op"))
            continue
        for fp, root in files:
            try:
                with open(fp, "r", encoding="utf-8") as fh:
                    src = fh.read()
            except (OSError, UnicodeDecodeError) as e:
                findings.append(Finding(
                    "JGL000", fp, 1, f"unreadable file: {e}"))
                continue
            out.append((fp, root, src))
    return out, findings


def analyze_paths(paths: Sequence[str]) -> List[Finding]:
    """Analyze every .py file under `paths` with the module-local
    rules (per-path mode: each file stands alone, reachability stops at
    its module boundary — see analyze_project for whole-program mode)."""
    sources, findings = collect_sources(paths)
    for fp, _, src in sources:
        findings.extend(analyze_source(src, fp))
    return findings


def analyze_project(paths: Sequence[str]) -> List[Finding]:
    """Whole-program mode: build one cross-module project index over
    every file, propagate traced (jit/scan/vmap) reachability through
    import-resolved call edges, run the module-local rules with those
    extra seeds, then the project-level concurrency rules (JGL009-011)
    on top. Suppression semantics are identical to per-path mode."""
    from factorvae_tpu.analysis import concurrency
    from factorvae_tpu.analysis.project import ProjectIndex

    sources, findings = collect_sources(paths)
    # One file reachable through two CLI paths (passed directly AND
    # under a directory argument) must index — and report — once.
    seen_paths: set = set()
    deduped = []
    for fp, root, src in sources:
        ap = os.path.abspath(fp)
        if ap in seen_paths:
            continue
        seen_paths.add(ap)
        deduped.append((fp, root, src))
    index = ProjectIndex(deduped)
    findings.extend(index.errors)          # unparseable files -> JGL000
    index.propagate_traced()
    per_file: Dict[str, List[Finding]] = {}
    for rec in index.records():
        per_file.setdefault(rec.path, []).extend(
            run_module_rules(rec.model))
    for rule_fn in concurrency.PROJECT_RULES:
        for f in rule_fn(index):
            per_file.setdefault(f.path, []).append(f)
    for rec in index.records():
        findings.extend(apply_suppressions(
            rec.src, rec.tree, rec.path, per_file.get(rec.path, [])))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def default_project_paths() -> List[str]:
    """`--project` with no paths: the installed package plus the repo's
    scripts/ next to it — the same surface the tier-1 per-path gate
    lints."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = [pkg]
    scripts = os.path.join(os.path.dirname(pkg), "scripts")
    if os.path.isdir(scripts):
        out.append(scripts)
    return out


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m factorvae_tpu.analysis",
        description="graftlint: JAX-aware static analysis "
                    "(tracer/host-sync/RNG/donation/dtype discipline)",
    )
    parser.add_argument("paths", nargs="*",
                        help="files or directories to analyze (required "
                             "unless --project, which defaults to the "
                             "installed package + scripts/)")
    parser.add_argument("--project", action="store_true",
                        help="whole-program mode: one cross-module index "
                             "(import-resolved call graph, thread-entry "
                             "reachability) over every path, enabling the "
                             "concurrency rules JGL009-011")
    parser.add_argument("--ir", action="store_true",
                        help="semantic backend: abstractly lower the "
                             "registered compiled programs (analysis/"
                             "ir.py) and audit jaxpr + post-SPMD HLO "
                             "(JIR001-004); composes with paths/"
                             "--project")
    parser.add_argument("--programs",
                        help="with --ir: comma-separated registry "
                             "subset (default: every registered "
                             "program)")
    parser.add_argument("--format", choices=("human", "json"),
                        default="human")
    parser.add_argument("--show-suppressed", action="store_true",
                        help="also list findings silenced by justified "
                             "suppressions")
    args = parser.parse_args(argv)

    paths = list(args.paths)
    if not paths and not args.ir:
        if not args.project:
            parser.error("paths are required without --project/--ir")
        paths = default_project_paths()
    if not paths and args.project:
        paths = default_project_paths()
    findings = []
    if paths:
        findings.extend(analyze_project(paths) if args.project
                        else analyze_paths(paths))
    if args.ir:
        from factorvae_tpu.analysis import ir

        names = None
        if args.programs:
            names = [n.strip() for n in args.programs.split(",")
                     if n.strip()]
        findings.extend(ir.analyze_programs(names=names))
    active = [f for f in findings if not f.suppressed]
    suppressed = [f for f in findings if f.suppressed]

    if args.format == "json":
        print(json.dumps({
            "findings": [f.to_dict() for f in active],
            "suppressed": [f.to_dict() for f in suppressed],
            "counts": {"active": len(active), "suppressed": len(suppressed)},
        }, indent=2))
    else:
        for f in active:
            print(f"{f.path}:{f.line}: {f.rule} {f.message}")
        if args.show_suppressed:
            for f in suppressed:
                print(f"{f.path}:{f.line}: {f.rule} [suppressed: "
                      f"{f.justification}] {f.message}")
        print(f"{len(active)} finding(s), {len(suppressed)} suppressed")
    return 1 if active else 0
