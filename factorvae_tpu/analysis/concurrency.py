"""graftlint concurrency rules JGL009-011 (whole-program mode only).

These rules consume the `ProjectIndex` (project.py) — the cross-module
call graph with thread/signal/HTTP entry reachability and the per-class
guarded-attribute inference — and judge the failure modes a
multithreaded serving/training system actually dies of:

- JGL009  a shared mutable attribute (or module-level container) is
          written from a thread-reachable scope and accessed from
          main-line code (or vice versa) without holding the lock that
          guards its other writes — the `/metrics`-scrape-vs-tick
          counter race.
- JGL010  a signal handler's reachable closure performs
          async-signal-unsafe work: logging, I/O, lock acquisition.
          CPython runs handlers between bytecodes of the interrupted
          frame; a handler that takes the very lock the interrupted
          code holds deadlocks the process on the way down.
- JGL011  a `daemon=True` thread whose target performs file writes,
          with no `join()` and no synchronous re-run of the same work
          at a barrier: process exit tears the artifact mid-write (the
          torn-artifact fault class the chaos harness injects
          dynamically — docs/robustness.md — caught statically here).

Every finding carries `thread_reachable=True` and an `entry_point`
naming the entry the reachability walk came through, which `--format
json` exposes (the CLI contract test pins the schema).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from factorvae_tpu.analysis.engine import Finding, _terminal_name
from factorvae_tpu.analysis.project import (
    Access,
    FnNode,
    ProjectIndex,
)

# ---------------------------------------------------------------------------
# JGL009 — unguarded cross-thread shared state


def _effective_held(w: Access) -> Set[Tuple]:
    """Locks held at a write: syntactic `with` context plus the locks
    the enclosing function inherits from every caller (fixpoint)."""
    return set(w.held) | set(w.fn.held)


def _describe_target(target: Tuple) -> str:
    if target[0] == "attr":
        _, module, cls, name = target
        return f"{cls}.{name}"
    _, module, name = target
    return f"{module}.{name}"


def _lock_name(lock_id: Tuple) -> str:
    _, module, cls, name = lock_id
    return f"self.{name}" if cls else name


def rule_jgl009(index: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    for target, writes in sorted(index.shared_writes().items(),
                                 key=lambda kv: kv[0]):
        if target[0] == "attr":
            if (target[1], target[2]) in index.http_handler_classes:
                # request-handler instances are born and die within one
                # request on one thread; their attrs cannot be shared
                continue
            readers = index.attr_readers(target[3])
        else:
            readers = index.global_readers((target[1], target[2]))
        t_write = [w for w in writes if index.thread_reachable(w.fn)]
        m_write = [w for w in writes if index.main_reachable(w.fn)]
        t_access = bool(t_write) or any(
            index.thread_reachable(r) for r in readers)
        m_access = bool(m_write) or any(
            index.main_reachable(r) for r in readers)
        if not ((t_write and m_access) or (m_write and t_access)):
            continue  # single-domain state: owned by one side, no race
        guarded = [w for w in writes if _effective_held(w)]
        owning: Set[Tuple] = set()
        if guarded:
            owning = set.intersection(
                *[_effective_held(w) for w in guarded])
        witness = ""
        for w in t_write:
            witness = index.entry_witness(w.fn)
            if witness:
                break
        if not witness:
            for r in readers:
                witness = index.entry_witness(r)
                if witness:
                    break
        # Composite-reader check (precise same-class `self.X` reads
        # only): once an owning lock exists, a cross-domain read that
        # skips it sees torn composites — an OrderedDict iterated
        # mid-eviction, a paired counter snapshot straddling a tick.
        # Reads co-located with a write site (the `self.d[k] = v` load
        # inside the store) dedup against the write finding.
        read_findings: List[Tuple[Access, str]] = []
        if owning:
            write_lines = {(w.fn.key, w.line) for w in writes}
            t_w = bool(t_write)
            m_w = bool(m_write)
            for r in index.self_reads_of(target):
                if (r.fn.key, r.line) in write_lines:
                    continue
                if _effective_held(r):
                    continue
                crosses = (t_w and index.main_reachable(r.fn)) or \
                    (m_w and index.thread_reachable(r.fn))
                if crosses:
                    read_findings.append((r, "read"))
        for w, what_kind in [(w, "write") for w in writes] \
                + read_findings:
            if what_kind == "write" and _effective_held(w):
                continue  # holds a lock (the owning one on every path
                #           that can reach it, by the fixpoint's
                #           conservative construction)
            what = _describe_target(target)
            if what_kind == "read":
                lock = ", ".join(sorted({_lock_name(x)
                                         for x in owning}))
                findings.append(Finding(
                    "JGL009", w.fn.model.path, w.line,
                    f"shared '{what}' read here without its owning "
                    f"lock ({lock} guards its writes) while the "
                    f"attribute crosses the thread/main-line boundary "
                    f"— a composite read (iteration, paired counters) "
                    f"interleaves with a locked mutation; hold the "
                    f"lock around the read too",
                    thread_reachable=True, entry_point=witness))
                continue
            if owning:
                lock = ", ".join(sorted({_lock_name(x)
                                         for x in owning}))
                msg = (
                    f"shared '{what}' written here without its owning "
                    f"lock ({lock} guards its other writes) while the "
                    f"attribute is reachable from both a thread entry "
                    f"({witness or 'thread'}) and main-line code — a "
                    f"concurrent scrape/tick interleaves the "
                    f"read-modify-write; hold the lock here too")
            else:
                msg = (
                    f"shared '{what}' mutated with NO lock while "
                    f"written/read from both a thread-reachable scope "
                    f"({witness or 'thread'}) and main-line code — "
                    f"`x += 1` and container mutation are not atomic "
                    f"across threads; guard every write with one lock "
                    f"(see obs/metrics.LatencyHistogram) or suppress "
                    f"with the invariant that serializes these "
                    f"accesses")
            findings.append(Finding(
                "JGL009", w.fn.model.path, w.line, msg,
                thread_reachable=True, entry_point=witness))
    return findings


# ---------------------------------------------------------------------------
# JGL010 — async-signal-unsafe signal handlers


#: call names (plain) that allocate/log/do I/O
UNSAFE_NAMES = {"print", "open"}
#: terminal attribute calls that log, flush, or take locks
UNSAFE_ATTRS = {"log", "write", "flush", "acquire", "makedirs",
                "warn", "warning", "error", "info", "debug",
                "exception"}
#: resolved dotted calls (module helpers that lock + write internally)
UNSAFE_RESOLVED = {"time.sleep", "os.makedirs", "os.replace",
                   "os.rename"}
#: timeline helpers — they funnel into MetricsLogger.log (lock + file
#: write) and are the exact shape the SIGTERM drain used to have
UNSAFE_TIMELINE = {"timeline_event", "timeline_span",
                   "timeline_span_at"}


def _lockish_context(index: ProjectIndex, fn: FnNode,
                     expr: ast.AST) -> Optional[str]:
    rec = index.modules[fn.module]
    lid = index._lock_id(rec, fn.cls, expr)
    if lid is not None:
        return _lock_name(lid)
    name = _terminal_name(expr)
    if name and "lock" in name.lower():
        return name
    return None


def _unsafe_sites(index: ProjectIndex,
                  fn: FnNode) -> List[Tuple[int, str]]:
    """(line, what) for every async-signal-unsafe operation in `fn`'s
    own body."""
    if fn.info is None:
        return []
    out: List[Tuple[int, str]] = []
    model = fn.model
    stack = list(ast.iter_child_nodes(fn.info.node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                lock = _lockish_context(index, fn, item.context_expr)
                if lock is not None:
                    out.append((node.lineno,
                                f"lock acquisition (`with {lock}`)"))
        elif isinstance(node, ast.Call):
            resolved = model.resolve(node.func)
            term = _terminal_name(node.func)
            if isinstance(node.func, ast.Name) \
                    and node.func.id in UNSAFE_NAMES:
                out.append((node.lineno, f"{node.func.id}() I/O"))
            elif term in UNSAFE_TIMELINE:
                out.append((node.lineno,
                            f"{term}() — locks the metrics stream and "
                            f"writes the RUN.jsonl"))
            elif resolved in UNSAFE_RESOLVED:
                out.append((node.lineno, f"{resolved}()"))
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr in UNSAFE_ATTRS:
                out.append((node.lineno, f".{node.func.attr}() call"))
        stack.extend(ast.iter_child_nodes(node))
    return out


def rule_jgl010(index: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    seen: Set[Tuple] = set()
    for entry in index.signal_entries():
        handler = entry.fn
        # Two hops: the handler's own body plus what it directly calls
        # (and one level below — the `request_drain -> timeline_event`
        # shape). Deeper and every handler would re-anchor its finding
        # inside the shared logging sink all code funnels through,
        # losing the actionable site.
        for fn in index.closure([handler], max_depth=2):
            for line, what in _unsafe_sites(index, fn):
                key = (handler.key, fn.module, line)
                if key in seen:
                    continue
                seen.add(key)
                where = "" if fn.key == handler.key else \
                    f" (reached through '{fn.qualname}')"
                findings.append(Finding(
                    "JGL010", fn.model.path, line,
                    f"signal handler '{handler.qualname}' performs "
                    f"async-signal-unsafe work{where}: {what}. CPython "
                    f"runs handlers between bytecodes of the "
                    f"interrupted frame — if the interrupted code "
                    f"holds the same (non-reentrant) lock, the process "
                    f"deadlocks on the way down. Set a threading.Event "
                    f"and return; do the drain work on the serving "
                    f"loop (serve/daemon.py's SIGTERM shape)",
                    thread_reachable=True,
                    entry_point=f"signal:{handler.label()}"))
    return findings


# ---------------------------------------------------------------------------
# JGL011 — daemon file-writer threads without a shutdown barrier


#: file-mutating operations a daemon thread must not be mid-way through
#: at process exit
WRITE_RESOLVED = {"os.replace", "os.rename", "json.dump",
                  "pickle.dump", "numpy.save", "shutil.move"}


def _file_write_sites(index: ProjectIndex,
                      fn: FnNode) -> List[Tuple[int, str]]:
    if fn.info is None:
        return []
    out: List[Tuple[int, str]] = []
    model = fn.model
    stack = list(ast.iter_child_nodes(fn.info.node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.Call):
            resolved = model.resolve(node.func)
            if resolved in WRITE_RESOLVED:
                out.append((node.lineno, resolved))
            elif isinstance(node.func, ast.Name) \
                    and node.func.id == "open":
                mode = None
                if len(node.args) >= 2 and isinstance(
                        node.args[1], ast.Constant):
                    mode = node.args[1].value
                for kw in node.keywords:
                    if kw.arg == "mode" and isinstance(kw.value,
                                                       ast.Constant):
                        mode = kw.value.value
                if isinstance(mode, str) and any(
                        c in mode for c in "wax+"):
                    out.append((node.lineno, f"open(..., {mode!r})"))
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "write":
                out.append((node.lineno, ".write()"))
        stack.extend(ast.iter_child_nodes(node))
    return out


def rule_jgl011(index: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    for spawn in index.thread_spawns:
        if not spawn.daemon or spawn.joined or not spawn.targets:
            continue
        # Barrier exemption: the target is ALSO called directly
        # somewhere (checkpoint.py's manifest flush runs synchronously
        # at every read-side barrier) — a dead daemon thread's work is
        # redone, so a torn write cannot be the surviving state.
        if any(index.direct_call_lines(t) for t in spawn.targets):
            continue
        sites: List[Tuple[int, str, str]] = []
        for fn in index.closure(spawn.targets):
            for line, what in _file_write_sites(index, fn):
                sites.append((line, what, fn.qualname))
        if not sites:
            continue
        sites.sort()
        shown = "; ".join(
            f"{what} in '{qn}' (line {line})"
            for line, what, qn in sites[:3])
        path = index.modules[spawn.module].path
        findings.append(Finding(
            "JGL011", path, spawn.line,
            f"daemon=True thread '{spawn.target_name}' performs file "
            f"writes ({shown}) with no join() and no synchronous "
            f"re-run of the same work at a barrier — daemon threads "
            f"are killed mid-write at interpreter exit, leaving a "
            f"torn artifact (the torn-file fault class chaos injects "
            f"dynamically). join it on every shutdown path, or make "
            f"the work re-runnable at a read-side barrier",
            thread_reachable=True,
            entry_point=f"thread:{spawn.targets[0].label()}"))
    return findings


PROJECT_RULES = (rule_jgl009, rule_jgl010, rule_jgl011)
