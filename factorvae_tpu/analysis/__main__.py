"""CLI entry: `python -m factorvae_tpu.analysis [paths] --format human|json`."""

import sys

from factorvae_tpu.analysis.engine import main

if __name__ == "__main__":
    sys.exit(main())
