"""graftlint rules JGL001–JGL008, JGL012 and JGL013.

Each rule is a function `(ModuleModel) -> list[Finding]`. JGL002 (key
reuse), JGL004 (read-after-donation) and the loop flavor of JGL001 share
`_Flow`, a small sequential abstract interpreter over a function body:
statements are processed in source order, `if` branches run on forked
state and merge conservatively (union of bad states), and loop bodies
are walked twice so a second iteration observes the state the first one
left behind — that second pass is what catches cross-iteration key reuse
and donated-buffer re-pass without any fixpoint machinery.

All rules are heuristic and name-based (see engine.py's module-local
resolution contract). They are tuned so the repo's sanctioned idioms —
`fold_in(base, c0)` streams, `k, sub = split(k)` rebinds, donate-then-
rebind epoch loops, one-scalar-per-epoch host reads — produce no
findings, while each documented failure mode does.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set

from factorvae_tpu.analysis.engine import (
    CACHE_DECORATORS,
    INSTRUMENTATION_WRAPPERS,
    JIT_WRAPPERS,
    KEY_DERIVERS,
    KEY_PRODUCERS,
    Finding,
    FuncInfo,
    ModuleModel,
    _local_nodes,
    _terminal_name,
)

HOST_SYNC_CALLS = {
    "jax.device_get": "jax.device_get",
    "numpy.asarray": "np.asarray",
    "numpy.array": "np.array",
}
HOST_SYNC_METHODS = {"item", "tolist", "block_until_ready", "to_py"}
HOST_CASTS = {"float", "int", "bool"}

# jnp constructor -> index of the positional dtype argument
DTYPE_POSITIONAL = {
    "zeros": 1,
    "ones": 1,
    "empty": 1,
    "full": 2,
    "arange": 3,
    "eye": 3,
    "linspace": 5,
}


def _target_names(targets) -> List[str]:
    out: List[str] = []

    def rec(t):
        if isinstance(t, ast.Name):
            out.append(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                rec(e)
        elif isinstance(t, ast.Starred):
            rec(t.value)

    for t in targets:
        rec(t)
    return out


def _root_name(expr: ast.AST) -> Optional[str]:
    """`out.factor_mu[j, kf]` -> "out"."""
    while isinstance(expr, (ast.Subscript, ast.Attribute)):
        expr = expr.value
    return expr.id if isinstance(expr, ast.Name) else None


def _terminates(stmts) -> bool:
    """Does a statement list end by leaving the enclosing block?"""
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break)
    )


def _chain_cached(model: ModuleModel, fn: Optional[FuncInfo]) -> bool:
    """Is `fn` or any enclosing function decorated with lru_cache/cache?"""
    cur = fn
    while cur is not None:
        for dec in cur.decorator_list():
            name = model.resolve(dec)
            if name is None and isinstance(dec, ast.Call):
                name = model.resolve(dec.func)
            if name in CACHE_DECORATORS:
                return True
        cur = cur.parent
    return False


def _has_jit_decorator(model: ModuleModel, fn: FuncInfo) -> bool:
    for dec in fn.decorator_list():
        if model.resolve(dec) in JIT_WRAPPERS:
            return True
        if isinstance(dec, ast.Call):
            if model.resolve(dec.func) in JIT_WRAPPERS:
                return True
            if model.resolve(dec.func) == "functools.partial" and dec.args \
                    and model.resolve(dec.args[0]) in JIT_WRAPPERS:
                return True
    return False


def _callee_key(model: ModuleModel, call: ast.Call) -> Optional[str]:
    """Lookup key for the donator/static tables: plain name, or
    "self.attr" for instance-cached wrappers."""
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
            and f.value.id == "self":
        return f"self.{f.attr}"
    return None


# ---------------------------------------------------------------------------
# sequential flow walker


class _Flow:
    """Source-order walk of one function body. Subclasses override
    `use(expr)` (an expression is evaluated), `assign(targets, value)`
    and `clear(names)`; state forking uses snapshot/restore/merge."""

    def __init__(self, model: ModuleModel, fn: FuncInfo):
        self.model = model
        self.fn = fn
        self.loop_depth = 0
        self.findings: Dict[tuple, Finding] = {}

    # -- hooks -------------------------------------------------------------

    def use(self, expr: ast.AST) -> None:
        raise NotImplementedError

    def assign(self, targets, value) -> None:
        self.clear(_target_names(targets))

    def clear(self, names: List[str]) -> None:
        raise NotImplementedError

    def snapshot(self):
        raise NotImplementedError

    def restore(self, snap) -> None:
        raise NotImplementedError

    def merge(self, other) -> None:
        raise NotImplementedError

    def report(self, rule: str, line: int, message: str, key=None) -> None:
        k = key if key is not None else (line, message)
        if k not in self.findings:
            self.findings[k] = Finding(rule, self.model.path, line, message)

    # -- walk --------------------------------------------------------------

    def run(self) -> List[Finding]:
        node = self.fn.node
        if isinstance(node, ast.Lambda):
            self.use(node.body)
        else:
            self.block(node.body)
        return list(self.findings.values())

    def block(self, stmts) -> None:
        for st in stmts:
            self.stmt(st)

    def stmt(self, st: ast.stmt) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef, ast.Import, ast.ImportFrom,
                           ast.Global, ast.Nonlocal, ast.Pass)):
            return
        if isinstance(st, ast.Assign):
            self.use(st.value)
            self.assign(st.targets, st.value)
        elif isinstance(st, (ast.AnnAssign, ast.AugAssign)):
            if st.value is not None:
                self.use(st.value)
                self.assign([st.target], st.value)
        elif isinstance(st, ast.Expr):
            self.use(st.value)
        elif isinstance(st, ast.Return):
            if st.value is not None:
                self.use(st.value)
        elif isinstance(st, ast.If):
            self.use(st.test)
            before = self.snapshot()
            self.block(st.body)
            after_body = self.snapshot()
            self.restore(before)
            self.block(st.orelse)
            # a branch that terminates (return/raise/...) never reaches the
            # code after the if — its state must not leak into the merge
            body_term = _terminates(st.body)
            orelse_term = bool(st.orelse) and _terminates(st.orelse)
            if body_term and not orelse_term:
                pass  # fall-through comes only from the orelse path
            elif orelse_term and not body_term:
                self.restore(after_body)
            else:
                self.merge(after_body)
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            self.use(st.iter)
            self.loop_depth += 1
            for _ in range(2):
                self.clear(_target_names([st.target]))
                self.block(st.body)
            self.loop_depth -= 1
            self.block(st.orelse)
        elif isinstance(st, ast.While):
            self.loop_depth += 1
            for _ in range(2):
                self.use(st.test)
                self.block(st.body)
            self.loop_depth -= 1
            self.block(st.orelse)
        elif isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                self.use(item.context_expr)
                if item.optional_vars is not None:
                    self.clear(_target_names([item.optional_vars]))
            self.block(st.body)
        elif isinstance(st, ast.Try):
            self.block(st.body)
            for h in st.handlers:
                self.block(h.body)
            self.block(st.orelse)
            self.block(st.finalbody)
        elif isinstance(st, ast.Match):
            self.use(st.subject)
            before = self.snapshot()
            arm_states = []
            for case in st.cases:
                self.restore(before)
                if case.guard is not None:
                    self.use(case.guard)
                self.block(case.body)
                if not _terminates(case.body):
                    arm_states.append(self.snapshot())
            # fall-through (no arm matched) + every non-terminating arm
            self.restore(before)
            for arm in arm_states:
                self.merge(arm)
        elif isinstance(st, ast.Delete):
            self.clear(_target_names(st.targets))
        else:
            for child in ast.iter_child_nodes(st):
                if isinstance(child, ast.expr):
                    self.use(child)


# ---------------------------------------------------------------------------
# JGL001 — host sync


def _shape_like(expr: ast.AST) -> bool:
    """float()/int() of a shape/len expression is static under trace."""
    if isinstance(expr, ast.Constant):
        return True
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and node.attr in ("shape", "ndim",
                                                            "size", "dtype"):
            return True
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id == "len":
            return True
    return False


def _subscript_has_slice(expr: ast.Subscript) -> bool:
    sl = expr.slice
    if isinstance(sl, ast.Slice):
        return True
    return isinstance(sl, ast.Tuple) and any(
        isinstance(e, ast.Slice) for e in sl.elts)


def _scalar_subscript(expr: ast.AST) -> bool:
    """`x[i]` / `x[i, j]` (one element) but NOT `x[lo:hi]` / `x[i, :]`
    (a chunk) — the granularity line JGL001's transfer flavor draws."""
    return isinstance(expr, ast.Subscript) and not _subscript_has_slice(expr)


class _HostLoopFlow(_Flow):
    """Loop flavor: per-element host pulls (float()/int()/.item(), or a
    np.asarray/device_get of a SLICE) inside a Python loop strictly
    deeper than the jitted call that produced the value — the
    eval/factors.py round-trip-per-row pattern. The sanctioned shape is
    one bulk `jax.device_get`/np.asarray per producing call (same loop
    depth — each chunk pulls its own output once), which rebinds the
    root to host numpy and clears the taint.

    Also the PUSH direction: `jax.device_put(x[i])` (a scalar-indexed
    element) inside a host loop is one tiny host->device transfer per
    element. CHUNK-granularity puts — a slice (`x[lo:hi]`) or a whole
    buffer per iteration — are the sanctioned out-of-core idiom
    (data/stream.py's double-buffered prefetch loop ships one gathered
    chunk per `device_put` while the device consumes the previous one)
    and stay silent."""

    HOST_PULLS = {"jax.device_get", "numpy.asarray", "numpy.array"}

    def __init__(self, model, fn, entry_names: Set[str]):
        super().__init__(model, fn)
        self.entry_names = entry_names
        self.device_vars: Dict[str, tuple] = {}  # name -> (line, loop_depth)

    def _flag(self, node, root, what):
        line, depth = self.device_vars[root]
        if self.loop_depth > depth:
            self.report(
                "JGL001", node.lineno,
                f"per-element {what} on '{root}' (device output of a jitted "
                f"call, line {line}) inside a Python loop — pull the whole "
                "chunk once with jax.device_get and index numpy arrays",
            )

    def use(self, expr: ast.AST) -> None:
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Name) and node.func.id in HOST_CASTS \
                    and len(node.args) == 1:
                root = _root_name(node.args[0])
                if root in self.device_vars:
                    self._flag(node, root, f"{node.func.id}() sync")
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "item" and not node.args:
                root = _root_name(node.func.value)
                if root in self.device_vars:
                    self._flag(node, root, ".item() sync")
            elif self.model.resolve(node.func) in self.HOST_PULLS \
                    and node.args:
                # a host pull in a loop DEEPER than the producing call is
                # one device fetch per iteration, not a bulk pull
                root = _root_name(node.args[0])
                if root in self.device_vars:
                    self._flag(node, root, "host pull")
            elif self.model.resolve(node.func) == "jax.device_put" \
                    and node.args and self.loop_depth > 0 \
                    and _scalar_subscript(node.args[0]):
                self.report(
                    "JGL001", node.lineno,
                    "per-element jax.device_put inside a host loop — one "
                    "tiny host->device transfer per element; ship "
                    "chunk-granularity slices and double-buffer the next "
                    "chunk while the device consumes the current one "
                    "(the data/stream.py ChunkStream idiom)",
                )

    def assign(self, targets, value) -> None:
        names = _target_names(targets)
        self.clear(names)
        if not isinstance(value, ast.Call):
            return
        resolved = self.model.resolve(value.func)
        if resolved in self.HOST_PULLS:
            return  # host numpy now
        key = _callee_key(self.model, value) or _terminal_name(value.func)
        if key in self.entry_names:
            for n in names:
                self.device_vars[n] = (value.lineno, self.loop_depth)

    def clear(self, names) -> None:
        for n in names:
            self.device_vars.pop(n, None)

    def snapshot(self):
        return dict(self.device_vars)

    def restore(self, snap) -> None:
        self.device_vars = dict(snap)

    def merge(self, other) -> None:
        for k, v in other.items():
            self.device_vars.setdefault(k, v)


def rule_jgl001(model: ModuleModel) -> List[Finding]:
    findings: List[Finding] = []
    # (a) host-sync primitives in traced code
    for fn in model.functions:
        if not fn.traced:
            continue
        for call in _local_nodes(fn.node, ast.Call):
            resolved = model.resolve(call.func)
            if resolved in HOST_SYNC_CALLS:
                findings.append(Finding(
                    "JGL001", model.path, call.lineno,
                    f"{HOST_SYNC_CALLS[resolved]} inside traced code "
                    f"('{fn.qualname}' is jit/scan/vmap-reachable) forces a "
                    "host sync or fails under trace",
                ))
            elif isinstance(call.func, ast.Attribute) \
                    and call.func.attr in HOST_SYNC_METHODS:
                findings.append(Finding(
                    "JGL001", model.path, call.lineno,
                    f".{call.func.attr}() inside traced code "
                    f"('{fn.qualname}') forces a host sync on a traced value",
                ))
            elif isinstance(call.func, ast.Name) \
                    and call.func.id in HOST_CASTS and len(call.args) == 1 \
                    and not _shape_like(call.args[0]):
                findings.append(Finding(
                    "JGL001", model.path, call.lineno,
                    f"{call.func.id}() on a traced value in "
                    f"'{fn.qualname}' breaks under jit "
                    "(ConcretizationTypeError) — keep it a jnp op",
                ))
    # (b) per-element pulls in host loops
    entry_names = model.traced_entry_names()
    for fn in model.functions:
        if fn.traced or isinstance(fn.node, ast.Lambda):
            continue
        findings.extend(_HostLoopFlow(model, fn, entry_names).run())
    return findings


# ---------------------------------------------------------------------------
# JGL002 — PRNG key reuse


class _KeyFlow(_Flow):
    FRESH = None  # sentinel: tracked, not yet consumed

    def __init__(self, model, fn):
        super().__init__(model, fn)
        self.keys: Dict[str, Optional[int]] = {}

    def use(self, expr: ast.AST) -> None:
        for name_node in self._consuming_names(expr):
            name = name_node.id
            if name not in self.keys:
                continue
            first = self.keys[name]
            if first is self.FRESH:
                self.keys[name] = name_node.lineno
            else:
                # second consumption — including the SAME line seen on the
                # walker's second loop pass (cross-iteration reuse)
                self.report(
                    "JGL002", name_node.lineno,
                    f"PRNG key '{name}' already consumed at line {first} — "
                    "interleave a split/fold_in (rebinding the name) before "
                    "reusing it",
                    key=("JGL002", name_node.lineno, name),
                )

    def _consuming_names(self, expr):
        """Name loads that constitute consumption: appearing inside a
        call that is not a key-deriving split/fold_in."""
        out: List[ast.Name] = []

        def walk_call(call: ast.Call):
            deriver = self.model.resolve(call.func) in KEY_DERIVERS
            walk(call.func)
            for a in list(call.args) + [kw.value for kw in call.keywords]:
                if deriver and isinstance(a, ast.Name):
                    continue  # sanctioned derivation read
                walk(a)

        def walk(n):
            if isinstance(n, ast.Call):
                walk_call(n)
            elif isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                out.append(n)
            else:
                for c in ast.iter_child_nodes(n):
                    walk(c)

        def top(n):
            if isinstance(n, ast.Call):
                walk_call(n)
            else:
                for c in ast.iter_child_nodes(n):
                    top(c)

        top(expr)
        return out

    def assign(self, targets, value) -> None:
        names = _target_names(targets)
        producer = isinstance(value, ast.Call) \
            and self.model.resolve(value.func) in KEY_PRODUCERS
        if producer:
            for n in names:
                self.keys[n] = self.FRESH
        else:
            self.clear(names)

    def clear(self, names) -> None:
        for n in names:
            self.keys.pop(n, None)

    def snapshot(self):
        return dict(self.keys)

    def restore(self, snap) -> None:
        self.keys = dict(snap)

    def merge(self, other) -> None:
        for name, st in other.items():
            if name in self.keys:
                cur = self.keys[name]
                if cur is self.FRESH and st is not self.FRESH:
                    self.keys[name] = st
            else:
                self.keys[name] = st


def rule_jgl002(model: ModuleModel) -> List[Finding]:
    findings: List[Finding] = []
    for fn in model.functions:
        if isinstance(fn.node, ast.Lambda):
            continue
        findings.extend(_KeyFlow(model, fn).run())
    return findings


# ---------------------------------------------------------------------------
# JGL003 — jit cache hazards


def rule_jgl003(model: ModuleModel) -> List[Finding]:
    findings: List[Finding] = []
    # (a) jax.jit(...) constructed in a per-call scope
    for node in ast.walk(model.tree):
        if not (isinstance(node, ast.Call)
                and model.resolve(node.func) in JIT_WRAPPERS):
            continue
        enc = model.enclosing_function(node)
        if enc is None or _chain_cached(model, enc):
            continue
        parent = model._parents.get(node)
        # Look through one-level instrumentation wrappers
        # (`self._f = watch_jit(jax.jit(...), name)`, obs/watchdog.py):
        # the instance-cached exemption keys on the ASSIGNMENT target,
        # not on the transparent wrapper in between. ONLY known
        # wrappers qualify — climbing through arbitrary calls would
        # exempt `self.out = jax.jit(f)(batch)` (a fresh jit invoked
        # per call), exactly what this rule exists to flag.
        while isinstance(parent, ast.Call) \
                and _terminal_name(parent.func) in INSTRUMENTATION_WRAPPERS:
            parent = model._parents.get(parent)
        if isinstance(parent, ast.Assign) and all(
            isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name)
            and t.value.id == "self" for t in parent.targets
        ):
            continue  # instance-cached wrapper (built once per object)
        if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue  # decorator form — handled below with the def's line
        findings.append(Finding(
            "JGL003", model.path, node.lineno,
            f"jax.jit constructed inside '{enc.qualname}' — a fresh jit per "
            "call retraces and recompiles every time; hoist to module "
            "level, lru_cache the factory, or store it on the instance",
        ))
    # (b) @jax.jit on a def nested in an uncached per-call scope
    for fn in model.functions:
        if isinstance(fn.node, ast.Lambda) or fn.parent is None:
            continue
        if _has_jit_decorator(model, fn) and not _chain_cached(model, fn):
            findings.append(Finding(
                "JGL003", model.path, fn.node.lineno,
                f"@jax.jit def '{fn.qualname}' nested in an uncached "
                "per-call scope recompiles on every call of "
                f"'{fn.parent.qualname}' — lru_cache the factory or hoist",
            ))
    # (c) unhashable literals at static_argnums positions
    for node in ast.walk(model.tree):
        if not isinstance(node, ast.Call):
            continue
        key = _callee_key(model, node)
        positions = model.static_args.get(key or "")
        if not positions:
            continue
        for p in positions:
            if p < len(node.args) and isinstance(
                node.args[p],
                (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
                 ast.SetComp),
            ):
                findings.append(Finding(
                    "JGL003", model.path, node.args[p].lineno,
                    f"unhashable literal passed at static_argnums position "
                    f"{p} of '{key}' — static args are jit-cache keys and "
                    "must be hashable (use a tuple)",
                ))
    return findings


# ---------------------------------------------------------------------------
# JGL004 — donated-buffer read-after-donation


class _DonationFlow(_Flow):
    def __init__(self, model, fn):
        super().__init__(model, fn)
        self.donated: Dict[str, int] = {}

    def use(self, expr: ast.AST) -> None:
        # reads first: a donated name loaded ANYWHERE (including as an
        # argument to the next donating call) is a read-after-donation
        if self.donated:
            for node in ast.walk(expr):
                if isinstance(node, ast.Name) \
                        and isinstance(node.ctx, ast.Load) \
                        and node.id in self.donated:
                    self.report(
                        "JGL004", node.lineno,
                        f"'{node.id}' was donated at line "
                        f"{self.donated[node.id]} (donate_argnums) and read "
                        "afterwards — XLA may have reused the buffer; "
                        "rebind the name from the call's output first",
                        key=("JGL004", node.lineno, node.id),
                    )
        # then register this statement's donations
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            key = _callee_key(self.model, node)
            positions = self.model.donators.get(key or "")
            if not positions:
                continue
            for p in positions:
                if p < len(node.args) and isinstance(node.args[p], ast.Name):
                    self.donated[node.args[p].id] = node.lineno

    def clear(self, names) -> None:
        for n in names:
            self.donated.pop(n, None)

    def snapshot(self):
        return dict(self.donated)

    def restore(self, snap) -> None:
        self.donated = dict(snap)

    def merge(self, other) -> None:
        for k, v in other.items():
            self.donated.setdefault(k, v)


def rule_jgl004(model: ModuleModel) -> List[Finding]:
    if not model.donators:
        return []
    findings: List[Finding] = []
    for fn in model.functions:
        if isinstance(fn.node, ast.Lambda):
            continue
        findings.extend(_DonationFlow(model, fn).run())
    return findings


# ---------------------------------------------------------------------------
# JGL005 — dtype drift in plan-governed hot paths


def rule_jgl005(model: ModuleModel) -> List[Finding]:
    if not model.hot_path:
        return []
    findings: List[Finding] = []
    for node in ast.walk(model.tree):
        if not isinstance(node, ast.Call):
            continue
        resolved = model.resolve(node.func)
        if not resolved or not resolved.startswith("jax.numpy."):
            continue
        ctor = resolved[len("jax.numpy."):]
        pos = DTYPE_POSITIONAL.get(ctor)
        if pos is None:
            continue
        if any(kw.arg == "dtype" for kw in node.keywords):
            continue
        if len(node.args) > pos:
            continue
        findings.append(Finding(
            "JGL005", model.path, node.lineno,
            f"jnp.{ctor} without an explicit dtype in a plan-governed hot "
            "path — this silently pins the backend default dtype (f32 for "
            "float fills, int32 for integer ranges) regardless of what the "
            "plan chose; pass dtype= explicitly",
        ))
    return findings


# ---------------------------------------------------------------------------
# JGL006 — bare print() in library modules


# Exempt by construction: CLI surfaces whose job IS stdout.
JGL006_EXEMPT_BASENAMES = {"cli.py", "__main__.py"}
# The metrics sink itself: MetricsLogger's echo/degradation prints are
# the terminal end of the routing this rule enforces.
JGL006_EXEMPT_SUFFIXES = ("factorvae_tpu/utils/logging.py",)


def _dunder_main_ranges(tree: ast.Module) -> List[tuple]:
    """(first, last) line ranges of top-level `if __name__ == ...`
    blocks — module smoke entries run as scripts, not as library code."""
    out = []
    for node in tree.body:
        if isinstance(node, ast.If) and any(
            isinstance(n, ast.Name) and n.id == "__name__"
            for n in ast.walk(node.test)
        ):
            out.append((node.lineno,
                        getattr(node, "end_lineno", node.lineno)))
    return out


def rule_jgl006(model: ModuleModel) -> List[Finding]:
    """Bare `print(` in a factorvae_tpu library module. Library output
    belongs on the MetricsLogger/timeline event stream (one RUN.jsonl
    per run, machine-readable, wandb-forwardable); stray prints
    interleave unstructured text into whatever stdout the caller owns
    (the bench's one-JSON-line contract, autotune's table output).
    Exempt: CLI entry files (cli.py, __main__.py), `main()` functions
    and anything nested in one, module-level `if __name__ == "__main__"`
    smoke blocks, and the logger module itself (the sink)."""
    norm = model.path.replace(os.sep, "/")
    if "factorvae_tpu/" not in norm:
        return []  # scripts/, tests/, bench.py own their stdout
    if os.path.basename(norm) in JGL006_EXEMPT_BASENAMES or any(
            norm.endswith(s) for s in JGL006_EXEMPT_SUFFIXES):
        return []
    guards = _dunder_main_ranges(model.tree)
    findings: List[Finding] = []
    for node in ast.walk(model.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"):
            continue
        if any(lo <= node.lineno <= hi for lo, hi in guards):
            continue
        fn = model.enclosing_function(node)
        cur, in_main = fn, False
        while cur is not None:
            if cur.name == "main":
                in_main = True
                break
            cur = cur.parent
        if in_main:
            continue
        where = f"'{fn.qualname}'" if fn is not None else "module level"
        findings.append(Finding(
            "JGL006", model.path, node.lineno,
            f"bare print() at {where} in a library module — route it "
            "through MetricsLogger.log (metrics/events) or the timeline "
            "so runs yield one coherent RUN.jsonl; CLI mains are exempt",
        ))
    return findings


# ---------------------------------------------------------------------------
# JGL007 — silent exception swallow in library code


# Call names (terminal attribute or plain name) that count as surfacing
# the failure: the MetricsLogger/timeline sinks, stdlib logging levels,
# warnings.warn, and print (stderr recipes in CLI-adjacent helpers).
JGL007_SURFACING_CALLS = {
    "log", "timeline_event", "print", "warn", "warning", "error",
    "exception", "debug", "info", "critical", "fail", "skip", "xfail",
}

BROAD_EXC_NAMES = {"Exception", "BaseException"}


def _broad_handler(h: ast.ExceptHandler) -> bool:
    """Bare `except:`, or a type (possibly in a tuple) resolving to
    Exception/BaseException. Narrow handlers (OSError, ValueError, ...)
    state what they expect and are out of scope."""
    if h.type is None:
        return True
    types = h.type.elts if isinstance(h.type, ast.Tuple) else [h.type]
    return any(_terminal_name(t) in BROAD_EXC_NAMES for t in types)


def _handler_walk(body):
    """ast.walk over handler statements WITHOUT descending into nested
    function/lambda definitions: a `return` (or a Load of the bound
    name) inside a callback the handler merely defines runs later, in
    another frame — it does not surface THIS exception, and counting it
    would let `except Exception: callbacks.append(lambda: ...)` pass as
    an explicit failure policy."""
    stack = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _handler_surfaces(h: ast.ExceptHandler) -> bool:
    """Does the handler body re-raise, return, log, or capture the
    exception into a value? Any of these makes the failure policy
    explicit; a body with none of them swallowed the error silently."""
    for node in _handler_walk(h.body):
        if isinstance(node, (ast.Raise, ast.Return)):
            return True
        if isinstance(node, ast.Call) \
                and _terminal_name(node.func) in JGL007_SURFACING_CALLS:
            return True
        # `except Exception as e: out["error"] = str(e)` — the bound
        # exception flows into a value the caller will see
        if h.name and isinstance(node, ast.Name) \
                and isinstance(node.ctx, ast.Load) and node.id == h.name:
            return True
    return False


def rule_jgl007(model: ModuleModel) -> List[Finding]:
    """Broad `except Exception` handlers in `factorvae_tpu/` library
    modules must make their failure policy explicit: re-raise, log the
    error (MetricsLogger / timeline_event / warnings / print-to-stderr),
    return an explicit error/fallback value, or convert the bound
    exception into a value. `except Exception: pass` (and fallthrough
    fallback assignments that never mention the error) hide real faults
    exactly where the self-healing machinery needs to see them
    (docs/robustness.md); deliberate best-effort swallows carry a
    justified suppression so the audit trail survives."""
    norm = model.path.replace(os.sep, "/")
    if "factorvae_tpu/" not in norm:
        return []  # scripts/, tests/, bench.py own their error policy
    findings: List[Finding] = []
    for node in ast.walk(model.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _broad_handler(node):
            continue
        if _handler_surfaces(node):
            continue
        what = "bare except:" if node.type is None else "except Exception"
        findings.append(Finding(
            "JGL007", model.path, node.lineno,
            f"{what} swallows the error silently — log it "
            "(MetricsLogger/timeline_event), re-raise, or return an "
            "explicit error value; a deliberate best-effort swallow "
            "needs a justified suppression",
        ))
    return findings


# ---------------------------------------------------------------------------
# JGL008 — wall-clock duration measurement in library code


def _is_walltime_call(model: ModuleModel, expr: ast.AST) -> bool:
    return isinstance(expr, ast.Call) \
        and model.resolve(expr.func) == "time.time" and not expr.args


def rule_jgl008(model: ModuleModel) -> List[Finding]:
    """`time.time()` used to MEASURE a duration — its value (directly
    or through an assigned name) participates in a subtraction — in
    `factorvae_tpu/` library code. The Timeline contract
    (utils/logging.py) is monotonic `time.perf_counter` for every
    span/duration: wall-clock `time.time()` jumps under NTP steps and
    DST, so a duration measured on it can come out negative or wildly
    wrong, and its records land on a DIFFERENT time base than the rest
    of the run's spans. `time.time()` as a TIMESTAMP (the `ts` field
    of metric records, checkpoint `created` stamps) never subtracts
    and stays exempt — that is exactly what a wall clock is for."""
    norm = model.path.replace(os.sep, "/")
    if "factorvae_tpu/" not in norm:
        return []  # scripts/, tests/, bench.py own their clocks
    # names bound to time.time() anywhere in the module (the engine's
    # standard name-based over-approximation)
    tracked: Set[str] = set()
    for node in ast.walk(model.tree):
        if isinstance(node, ast.Assign) \
                and _is_walltime_call(model, node.value):
            tracked.update(_target_names(node.targets))
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)) \
                and node.value is not None \
                and _is_walltime_call(model, node.value):
            tracked.update(_target_names([node.target]))

    def measures(expr: ast.AST) -> bool:
        return _is_walltime_call(model, expr) or (
            isinstance(expr, ast.Name) and expr.id in tracked)

    findings: List[Finding] = []
    for node in ast.walk(model.tree):
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub) \
                and (measures(node.left) or measures(node.right)):
            findings.append(Finding(
                "JGL008", model.path, node.lineno,
                "duration measured with wall-clock time.time() — the "
                "Timeline contract is monotonic time.perf_counter "
                "(an NTP step or DST jump corrupts the span, and the "
                "value shares no time base with the run's spans); "
                "keep time.time() for record timestamps only",
            ))
    return findings


# ---------------------------------------------------------------------------
# JGL012 — blocking network/synchronization call without a timeout


# resolved callable -> number of positional args at which the timeout
# parameter is covered positionally (urlopen(url, data, timeout) -> 3;
# create_connection(addr, timeout) -> 2; HTTP*Connection(host, port,
# timeout) -> 3). A `timeout=` keyword always satisfies the rule.
JGL012_TIMEOUT_CALLS = {
    "urllib.request.urlopen": 3,
    "socket.create_connection": 2,
    "http.client.HTTPConnection": 3,
    "http.client.HTTPSConnection": 3,
    "requests.get": None,
    "requests.post": None,
    "requests.put": None,
    "requests.delete": None,
    "requests.head": None,
    "requests.patch": None,
    "requests.request": None,
}

# constructors whose zero-arg `.wait()` blocks forever
JGL012_WAITABLE_CTORS = {"threading.Event", "threading.Condition"}


def _jgl012_wait_targets(model: ModuleModel) -> Set[str]:
    """Names module-locally bound to `threading.Event()` /
    `threading.Condition(...)` — plain locals ("done") and
    self-attributes ("self._stop") alike."""
    tracked: Set[str] = set()
    for node in ast.walk(model.tree):
        if not isinstance(node, ast.Assign):
            continue
        if not (isinstance(node.value, ast.Call) and model.resolve(
                node.value.func) in JGL012_WAITABLE_CTORS):
            continue
        for t in node.targets:
            if isinstance(t, ast.Name):
                tracked.add(t.id)
            elif isinstance(t, ast.Attribute) \
                    and isinstance(t.value, ast.Name) \
                    and t.value.id == "self":
                tracked.add(f"self.{t.attr}")
    return tracked


def _jgl012_wait_receiver(func: ast.Attribute) -> Optional[str]:
    """'done' for `done.wait()`, 'self._x' for `self._x.wait()`."""
    v = func.value
    if isinstance(v, ast.Name):
        return v.id
    if isinstance(v, ast.Attribute) and isinstance(v.value, ast.Name) \
            and v.value.id == "self":
        return f"self.{v.attr}"
    return None


def rule_jgl012(model: ModuleModel) -> List[Finding]:
    """Blocking network or synchronization call without an explicit
    timeout in `factorvae_tpu/` library code. The serving plane
    (ISSUE 17) is a mesh of sockets — router forwards, remote
    join/download, autoscale scrapes, readiness probes — and every
    untimed blocking call in it is a hang that outlives the peer: a
    worker that dies mid-recv parks the caller forever, invisible to
    the watcher that would have healed it. Two shapes are flagged:
    HTTP/socket calls (`urlopen`, `http.client.*Connection`,
    `socket.create_connection`, `requests.*`) with neither a
    `timeout=` keyword nor the positional timeout slot filled, and
    zero-arg `.wait()` on a `threading.Event`/`Condition` (blocks
    forever; `wait(t)` in a liveness-checking loop keeps the caller
    able to notice a dead peer). Deliberate untimed blocking carries a
    justified suppression."""
    norm = model.path.replace(os.sep, "/")
    if "factorvae_tpu/" not in norm:
        return []  # scripts/, tests/, bench.py own their blocking
    tracked = _jgl012_wait_targets(model)
    findings: List[Finding] = []
    for node in ast.walk(model.tree):
        if not isinstance(node, ast.Call):
            continue
        if any(kw.arg is None for kw in node.keywords):
            continue  # **kwargs may carry timeout — benefit of doubt
        if any(kw.arg == "timeout" for kw in node.keywords):
            continue
        resolved = model.resolve(node.func)
        if resolved in JGL012_TIMEOUT_CALLS:
            slot = JGL012_TIMEOUT_CALLS[resolved]
            if slot is not None and len(node.args) >= slot:
                continue
            findings.append(Finding(
                "JGL012", model.path, node.lineno,
                f"{resolved} without an explicit timeout — an untimed "
                "network call hangs forever when the peer dies "
                "mid-exchange; pass timeout= (the serving plane's "
                "watcher can only heal what returns)",
            ))
            continue
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "wait" and not node.args:
            recv = _jgl012_wait_receiver(node.func)
            if recv is not None and recv in tracked:
                findings.append(Finding(
                    "JGL012", model.path, node.lineno,
                    f"untimed {recv}.wait() on a threading "
                    "Event/Condition blocks forever if the notifier "
                    "dies — use wait(t) in a loop that can check "
                    "peer/thread liveness; a deliberate forever-block "
                    "needs a justified suppression",
                ))
    return findings


# ---------------------------------------------------------------------------
# JGL013 — same-function timeline_span_begin/_end pairing


def _jgl013_finally_nodes(func_node: ast.AST) -> Set[int]:
    """ids of every AST node lexically inside a `finally:` block of
    `func_node` (nested Trys included)."""
    protected: Set[int] = set()
    for node in ast.walk(func_node):
        if isinstance(node, ast.Try):
            for stmt in node.finalbody:
                for sub in ast.walk(stmt):
                    protected.add(id(sub))
    return protected


def rule_jgl013(model: ModuleModel) -> List[Finding]:
    """`timeline_span_begin` paired with `timeline_span_end` in the
    SAME function in `factorvae_tpu/` library code. The begin/end token
    API (utils/logging.py) exists for exactly one caller shape: a span
    opened on one thread and closed on another (the tick scheduler's
    queue-wait spans — submit() opens, the scheduler loop closes).
    Pairing them inside one function re-implements the `timeline_span`
    context manager by hand, and almost always wrong: without
    try/finally an exception between the calls leaks an open span the
    stream never sees the end of (the trace tree shows a request stuck
    forever in a stage it left), and with try/finally it is just the
    context manager, verbose. Cross-function begin/end — the sanctioned
    handoff — produces no finding."""
    norm = model.path.replace(os.sep, "/")
    if "factorvae_tpu/" not in norm:
        return []  # scripts/, tests/, bench.py own their instrumentation
    begins: Dict[ast.AST, List[ast.Call]] = {}
    ends: Dict[ast.AST, List[ast.Call]] = {}
    for node in ast.walk(model.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _terminal_name(node.func)
        if name not in ("timeline_span_begin", "timeline_span_end"):
            continue
        info = model.enclosing_function(node)
        if info is None:
            continue
        (begins if name == "timeline_span_begin" else ends).setdefault(
            info.node, []).append(node)
    findings: List[Finding] = []
    for func_node, begin_calls in begins.items():
        end_calls = ends.get(func_node)
        if not end_calls:
            continue  # begin-only: the cross-thread handoff, sanctioned
        protected = _jgl013_finally_nodes(func_node)
        if all(id(e) in protected for e in end_calls):
            msg = ("timeline_span_begin/timeline_span_end paired in one "
                   "function — this hand-rolls the timeline_span context "
                   "manager; the token API is for cross-thread handoff "
                   "only, use the context-manager form")
        else:
            msg = ("timeline_span_begin paired with timeline_span_end in "
                   "the same function without try/finally — an exception "
                   "between them leaks an open span (the trace tree shows "
                   "the request stuck in that stage forever); use the "
                   "timeline_span context-manager form")
        findings.append(Finding(
            "JGL013", model.path, min(b.lineno for b in begin_calls), msg,
        ))
    return findings


ALL_RULES = (rule_jgl001, rule_jgl002, rule_jgl003, rule_jgl004,
             rule_jgl005, rule_jgl006, rule_jgl007, rule_jgl008,
             rule_jgl012, rule_jgl013)
