"""Whole-program project index: the cross-module half of graftlint.

The module-local engine (engine.py) deliberately stops at module
boundaries; this index stitches the boundaries back together for the
analyses that are meaningless without them:

- **Cross-module call graph.** Every module's `ModuleModel` already
  resolves names through its import-alias table; the index uses that to
  link `step(3)` in `a.py` to `def step` in `b.py` when `a` wrote
  `from b import step` (or calls `b.step(...)`). Attribute calls that
  no import resolves (`daemon.handle_batch(...)`, `self.registry.get`)
  fall back to project-wide NAME matching — the same documented
  over-approximation the module-local engine uses, widened to the
  project: an edge too many makes reachability conservative, an edge
  too few makes it blind.

- **Thread-entry reachability.** Entry points are marked where
  concurrency is born: `threading.Thread(target=...)` /
  `ThreadPoolExecutor.submit(fn, ...)` targets, `signal.signal`
  handlers, and `do_*` methods of `http.server` request-handler
  classes. A function is *thread-reachable* when the call graph
  connects it to any such entry — that is the scope set the JGL009-011
  rules (concurrency.py) judge. `__call__` methods are a special case:
  they are invoked through variables the static graph cannot follow,
  so once the project has any thread entry at all they conservatively
  join the thread-reachable set (the watchdog's `WatchedJit.__call__`
  runs on whatever thread dispatches the jit).

- **Main-line reachability.** The dual set: everything reachable
  without crossing a thread entry (seeded from every function that is
  not itself an entry target, plus module-level code). A function can
  be in BOTH sets — `ModelRegistry.get` runs on the HTTP handler
  thread and on the stdin tick loop — and that dual membership is
  exactly what makes its unguarded counters a race.

- **Lock inference.** A class's lock attributes are the `self.X =
  threading.Lock()/RLock()` assignments (module-level locks the same
  way); an attribute written under `with self.X:` is *guarded by* X.
  Lock HELD-ness propagates through the call graph by intersection:
  a method called only from sites that hold the lock (the daemon's
  `_dispatch`/`_respond` under `handle_batch`'s tick lock) inherits
  it; one unlocked call site and the inherited set collapses — a
  conservative fixpoint, so propagation can only excuse a write when
  EVERY path to it holds the lock.

- **Cross-module traced propagation.** Module-local jit/scan/vmap
  reachability seeds re-propagate across import-resolved edges (only
  those: name-matched edges are too blunt to taint tracing), so a
  traced scan body calling a helper in another module drags JGL001's
  host-sync check along with it.

Like the engine, this is stdlib-only `ast` — nothing under analysis is
imported or executed.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from factorvae_tpu.analysis.engine import (
    Finding,
    FuncInfo,
    ModuleModel,
    _terminal_name,
)

#: constructors whose result is a lock for guarded-attribute inference
LOCK_FACTORIES = {"threading.Lock", "threading.RLock"}

#: module-level constructors whose instances are tracked shared globals
GLOBAL_CONTAINER_CALLS = {
    "dict", "list", "set",
    "collections.OrderedDict", "collections.defaultdict",
    "collections.deque", "collections.Counter",
}

#: method names that mutate their receiver (the write half of JGL009's
#: shared-state tracking; reads are matched by attribute name)
MUTATORS = {
    "append", "extend", "insert", "add", "update", "setdefault",
    "pop", "popitem", "remove", "discard", "clear", "appendleft",
    "move_to_end", "write",
}

#: attribute-call names EXCLUDED from the project-wide name-match
#: fallback: they are overwhelmingly container/file methods
#: (`self._cache.clear()`, `fh.flush()`), and linking them to a
#: same-named def somewhere in the project manufactures absurd edges
#: (a dict `.clear()` in the daemon must not make a linter flow-walker
#: class thread-reachable). Same-class `self.clear()` calls still
#: resolve precisely before this fallback is consulted.
NO_NAME_MATCH = MUTATORS | {"flush", "close", "read", "result", "join",
                            "start", "set", "wait", "get_indexer"}

#: base-class name suffix marking stdlib HTTP request handlers — their
#: do_* methods run per request, potentially off the accept thread
HTTP_HANDLER_SUFFIX = "HTTPRequestHandler"

#: HTTP handler methods treated as entries (besides do_*)
HTTP_ENTRY_METHODS = {"log_message", "log_error"}

#: entry kinds in the index (Entry.kind values)
# "thread"   threading.Thread(target=...)
# "executor" <pool>.submit(fn, ...)
# "signal"   signal.signal(SIG, handler)
# "http"     do_*/log_* methods of *HTTPRequestHandler subclasses
# "callable" __call__ methods (conservative, see _mark_callables)


# ---------------------------------------------------------------------------
# data model


@dataclasses.dataclass
class ModuleRec:
    name: str                  # dotted module name ("pkg.sub.mod")
    path: str
    src: str
    tree: ast.Module
    model: ModuleModel


class FnNode:
    """One function (or the pseudo-node for a module's top-level code)
    in the project graph."""

    __slots__ = ("module", "model", "info", "cls", "key", "calls",
                 "writes", "self_reads", "attr_reads", "global_reads",
                 "held")

    def __init__(self, module: str, model: ModuleModel,
                 info: Optional[FuncInfo], cls: Optional[str]):
        self.module = module
        self.model = model
        self.info = info
        self.cls = cls
        qual = info.qualname if info is not None else "<module>"
        self.key = (module, qual)
        self.calls: List["CallSite"] = []
        self.writes: List["Access"] = []
        # precisely-attributable reads: `self.X` loads inside this
        # class's own methods (JGL009's composite-reader check)
        self.self_reads: List["Access"] = []
        self.attr_reads: Set[str] = set()
        self.global_reads: Set[Tuple[str, str]] = set()
        # locks held at EVERY call site of this function (fixpoint)
        self.held: Set[Tuple] = set()

    @property
    def name(self) -> str:
        return self.info.name if self.info is not None else "<module>"

    @property
    def qualname(self) -> str:
        return self.key[1]

    def label(self) -> str:
        return f"{self.module}.{self.qualname}"


@dataclasses.dataclass
class CallSite:
    callee: FnNode
    line: int
    held: frozenset            # lock ids held syntactically at the site
    precise: bool              # import/local/self-resolved (not name-match)


@dataclasses.dataclass
class Access:
    """One shared-state WRITE: an augmented assignment, a subscript
    store, a `del x[...]`, or a mutator method call. Plain rebinds
    (`self.x = v`, `G = v`) are CPython-atomic reference swaps and are
    deliberately not collected."""

    target: Tuple               # ("attr", module, cls, name) | ("global", module, name)
    fn: FnNode
    line: int
    kind: str                   # "aug" | "subscript" | "mutcall" | "del" | "read"
    held: frozenset             # effective locks: syntactic at the site


@dataclasses.dataclass
class Entry:
    kind: str                   # "thread" | "executor" | "signal" | "http" | "callable"
    fn: FnNode
    line: int


@dataclasses.dataclass
class ThreadSpawn:
    module: str
    line: int
    targets: List[FnNode]
    target_name: str
    daemon: bool
    handle: Optional[str]       # name or "self.X" the Thread was bound to
    joined: bool = False


# ---------------------------------------------------------------------------
# index


class ProjectIndex:
    def __init__(self, sources: Sequence[Tuple[str, Optional[str], str]]):
        """`sources` is collect_sources() output:
        [(file_path, package_root_or_None, src)]."""
        self.modules: Dict[str, ModuleRec] = {}
        self.errors: List[Finding] = []
        for path, root, src in sources:
            name = self._module_name(path, root)
            try:
                tree = ast.parse(src)
            except SyntaxError as e:
                self.errors.append(Finding(
                    "JGL000", path, e.lineno or 1,
                    f"unparseable file: {e.msg}"))
                continue
            if name in self.modules:
                # Two inputs deriving the same dotted name would
                # silently shadow each other — the engine's contract is
                # that nothing passed to the gate is ever dropped
                # quietly. Fail loudly (JGL000 is unsuppressible) and
                # still analyze the file under a disambiguated key so
                # its module-local findings are not lost; cross-module
                # edges keep resolving to the FIRST claimant.
                self.errors.append(Finding(
                    "JGL000", path, 1,
                    f"module name {name!r} collides with "
                    f"{self.modules[name].path} in this project index — "
                    f"cross-module resolution is ambiguous; pass "
                    f"distinct roots or rename one file"))
                name = f"{name}@{len(self.modules)}"
            self.modules[name] = ModuleRec(
                name, path, src, tree, ModuleModel(path, src, tree))

        self.fns: List[FnNode] = []
        self.fns_by_name: Dict[str, List[FnNode]] = {}
        self._by_module_name: Dict[Tuple[str, str], List[FnNode]] = {}
        self._node_to_fn: Dict[Tuple[str, int], FnNode] = {}
        self.module_nodes: Dict[str, FnNode] = {}
        # lock registries: (module, cls) -> {attr}, module -> {global}
        self.class_locks: Dict[Tuple[str, str], Set[str]] = {}
        self.module_locks: Dict[str, Set[str]] = {}
        # tracked module-level mutable containers: (module, name)
        self.globals: Set[Tuple[str, str]] = set()
        self.entries: List[Entry] = []
        self.thread_spawns: List[ThreadSpawn] = []
        # stdlib HTTP request-handler classes: instances are created
        # per request and die with it, so their attributes are
        # request-confined — JGL009 exempts them
        self.http_handler_classes: Set[Tuple[str, str]] = set()

        for rec in self.modules.values():
            self._collect_structure(rec)
        for rec in self.modules.values():
            self._collect_entries(rec)
        self._mark_callables()
        for rec in self.modules.values():
            self._walk_module(rec)
        self._mark_spawn_joins()
        self._propagate_held()
        self._compute_reachability()

    # ---- naming ----------------------------------------------------------

    @staticmethod
    def _module_name(path: str, root: Optional[str]) -> str:
        """Dotted module name as the code's own imports would spell it
        — anchored at the outermost PACKAGE directory, not at the CLI
        argument. A root that is itself a package (`--project
        factorvae_tpu`) keeps its basename; a plain container root
        (the repo checkout, a fixtures folder) contributes no prefix
        and leading non-package directories are path, not package —
        otherwise `--project .` would name modules `repo.pkg.mod`
        while imports resolve `pkg.mod`, silently degrading every
        cross-module edge to a name match."""
        path = os.path.abspath(path)
        if root is None:
            return os.path.splitext(os.path.basename(path))[0]
        root = os.path.abspath(root)
        rel = os.path.relpath(path, root)
        parts = rel.split(os.sep)
        parts[-1] = os.path.splitext(parts[-1])[0]
        if os.path.exists(os.path.join(root, "__init__.py")):
            parts = [os.path.basename(root)] + parts
        else:
            base = root
            while len(parts) > 1 and not os.path.exists(
                    os.path.join(base, parts[0], "__init__.py")):
                base = os.path.join(base, parts[0])
                parts.pop(0)
        if parts[-1] == "__init__":
            parts.pop()
        return ".".join(p for p in parts if p)

    def records(self) -> List[ModuleRec]:
        return list(self.modules.values())

    # ---- structure -------------------------------------------------------

    def _collect_structure(self, rec: ModuleRec) -> None:
        cls_of: Dict[ast.AST, Optional[str]] = {}

        def visit(node, cls):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    visit(child, child.name)
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef, ast.Lambda)):
                    cls_of[child] = cls
                    # nested defs get cls=None: their `self` (if any) is
                    # a closure variable, not this class's instance
                    visit(child, None)
                else:
                    visit(child, cls)

        visit(rec.tree, None)
        for info in rec.model.functions:
            fn = FnNode(rec.name, rec.model, info, cls_of.get(info.node))
            self._register(fn)
            self._node_to_fn[(rec.name, id(info.node))] = fn
        mod_fn = FnNode(rec.name, rec.model, None, None)
        self.module_nodes[rec.name] = mod_fn
        self.fns.append(mod_fn)

        # lock attributes / lock globals / tracked container globals
        for node in ast.walk(rec.tree):
            if not isinstance(node, ast.Assign) \
                    or not isinstance(node.value, ast.Call):
                continue
            resolved = rec.model.resolve(node.value.func)
            for tgt in node.targets:
                if resolved in LOCK_FACTORIES:
                    if isinstance(tgt, ast.Attribute) \
                            and isinstance(tgt.value, ast.Name) \
                            and tgt.value.id == "self":
                        owner = rec.model.enclosing_function(node)
                        cls = cls_of.get(owner.node) if owner else None
                        if cls:
                            self.class_locks.setdefault(
                                (rec.name, cls), set()).add(tgt.attr)
                    elif isinstance(tgt, ast.Name) \
                            and rec.model.enclosing_function(node) is None:
                        self.module_locks.setdefault(
                            rec.name, set()).add(tgt.id)
                elif (resolved in GLOBAL_CONTAINER_CALLS
                      and isinstance(tgt, ast.Name)
                      and rec.model.enclosing_function(node) is None):
                    self.globals.add((rec.name, tgt.id))
        for node in rec.tree.body:
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, (ast.Dict, ast.List, ast.Set)):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        self.globals.add((rec.name, tgt.id))

    def _register(self, fn: FnNode) -> None:
        self.fns.append(fn)
        self.fns_by_name.setdefault(fn.name, []).append(fn)
        self._by_module_name.setdefault(
            (fn.module, fn.name), []).append(fn)

    def fn_of(self, module: str, node: ast.AST) -> Optional[FnNode]:
        return self._node_to_fn.get((module, id(node)))

    def named_in(self, module: str, name: str) -> List[FnNode]:
        return self._by_module_name.get((module, name), [])

    # ---- call / target resolution ---------------------------------------

    def _resolve_targets(self, rec: ModuleRec, cls: Optional[str],
                         expr: ast.AST) -> Tuple[List[FnNode], bool]:
        """FnNodes a function-valued expression (call callee, thread
        target) can denote, plus whether the link is PRECISE (import /
        local / same-class) or a project-wide name match."""
        if isinstance(expr, ast.Lambda):
            fn = self.fn_of(rec.name, expr)
            return ([fn], True) if fn is not None else ([], True)
        resolved = rec.model.resolve(expr)
        if resolved and "." in resolved:
            prefix, _, last = resolved.rpartition(".")
            if prefix in self.modules:
                hits = self.named_in(prefix, last)
                if hits:
                    return hits, True
            elif isinstance(expr, ast.Name):
                # `from subprocess import run; run(...)`: the bare name
                # ALIAS-resolves outside the project, so it cannot
                # denote a local def — falling through to the local
                # name match would link an unrelated `def run` (and,
                # being a "precise" edge, taint traced propagation)
                return [], True
        if isinstance(expr, ast.Name):
            hits = self.named_in(rec.name, expr.id)
            return hits, True
        if isinstance(expr, ast.Attribute):
            name = expr.attr
            if isinstance(expr.value, ast.Name) and expr.value.id == "self" \
                    and cls is not None:
                same = [f for f in self.named_in(rec.name, name)
                        if f.cls == cls]
                if same:
                    return same, True
            # External-library calls resolve to nothing, not to a
            # name match: `subprocess.run(...)` / `np.asarray(...)` /
            # `ocp.args.Composite(...)` are rooted at an IMPORT alias,
            # so they cannot denote a project function — linking them
            # by terminal name would drag unrelated same-named defs
            # into reachability.
            base = expr.value
            while isinstance(base, ast.Attribute):
                base = base.value
            if isinstance(base, ast.Name) and base.id in rec.model.aliases:
                return [], True
            if name in NO_NAME_MATCH:
                return [], False
            return list(self.fns_by_name.get(name, [])), False
        return [], True

    # ---- entries ---------------------------------------------------------

    def _entry_cls(self, rec: ModuleRec, node: ast.AST) -> Optional[str]:
        owner = rec.model.enclosing_function(node)
        if owner is None:
            return None
        fn = self.fn_of(rec.name, owner.node)
        return fn.cls if fn is not None else None

    def _collect_entries(self, rec: ModuleRec) -> None:
        parents = rec.model._parents
        for node in ast.walk(rec.tree):
            if isinstance(node, ast.ClassDef):
                if any(_terminal_name(b) is not None
                       and str(_terminal_name(b)).endswith(
                           HTTP_HANDLER_SUFFIX)
                       for b in node.bases):
                    self.http_handler_classes.add((rec.name, node.name))
                    for child in node.body:
                        if isinstance(child, (ast.FunctionDef,
                                              ast.AsyncFunctionDef)) \
                                and (child.name.startswith("do_")
                                     or child.name in HTTP_ENTRY_METHODS):
                            fn = self.fn_of(rec.name, child)
                            if fn is not None:
                                self.entries.append(Entry(
                                    "http", fn, child.lineno))
                continue
            if not isinstance(node, ast.Call):
                continue
            resolved = rec.model.resolve(node.func)
            cls = self._entry_cls(rec, node)
            if resolved == "threading.Thread":
                target = next((kw.value for kw in node.keywords
                               if kw.arg == "target"), None)
                if target is None:
                    continue
                targets, _ = self._resolve_targets(rec, cls, target)
                daemon = any(
                    kw.arg == "daemon"
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value is True for kw in node.keywords)
                handle = None
                parent = parents.get(node)
                if isinstance(parent, ast.Assign):
                    for tgt in parent.targets:
                        if isinstance(tgt, ast.Name):
                            handle = tgt.id
                        elif isinstance(tgt, ast.Attribute) \
                                and isinstance(tgt.value, ast.Name) \
                                and tgt.value.id == "self":
                            handle = f"self.{tgt.attr}"
                self.thread_spawns.append(ThreadSpawn(
                    rec.name, node.lineno, targets,
                    _terminal_name(target) or "<lambda>", daemon, handle))
                for fn in targets:
                    self.entries.append(Entry("thread", fn, node.lineno))
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "submit" and node.args:
                targets, _ = self._resolve_targets(rec, cls, node.args[0])
                for fn in targets:
                    self.entries.append(Entry("executor", fn, node.lineno))
            elif resolved == "signal.signal" and len(node.args) >= 2:
                targets, _ = self._resolve_targets(rec, cls, node.args[1])
                for fn in targets:
                    self.entries.append(Entry("signal", fn, node.lineno))

    def _mark_callables(self) -> None:
        """`__call__` runs on whatever thread invokes the object —
        untrackable statically — so once the project spawns ANY thread,
        every `__call__` conservatively joins the thread-reachable set
        (it stays main-reachable too)."""
        if not any(e.kind in ("thread", "executor", "http")
                   for e in self.entries):
            return
        for fn in self.fns_by_name.get("__call__", []):
            self.entries.append(Entry(
                "callable", fn,
                getattr(fn.info.node, "lineno", 1) if fn.info else 1))

    def _mark_spawn_joins(self) -> None:
        for spawn in self.thread_spawns:
            if spawn.handle is None:
                continue
            rec = self.modules[spawn.module]
            for node in ast.walk(rec.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "join"):
                    continue
                v = node.func.value
                joined_name = None
                if isinstance(v, ast.Name):
                    joined_name = v.id
                elif isinstance(v, ast.Attribute) \
                        and isinstance(v.value, ast.Name) \
                        and v.value.id == "self":
                    joined_name = f"self.{v.attr}"
                if joined_name == spawn.handle:
                    spawn.joined = True
                    break

    # ---- the per-function walk (calls, writes, reads, held locks) --------

    def _lock_id(self, rec: ModuleRec, cls: Optional[str],
                 expr: ast.AST) -> Optional[Tuple]:
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self" and cls is not None \
                and expr.attr in self.class_locks.get((rec.name, cls),
                                                      ()):
            return ("L", rec.name, cls, expr.attr)
        if isinstance(expr, ast.Name) \
                and expr.id in self.module_locks.get(rec.name, ()):
            return ("L", rec.name, "", expr.id)
        return None

    def _global_id(self, rec: ModuleRec,
                   name_node: ast.Name) -> Optional[Tuple[str, str]]:
        """Tracked-global id for a Name, following from-imports
        (`from m import COUNTS` -> ("m", "COUNTS"))."""
        resolved = rec.model.aliases.get(name_node.id, name_node.id)
        if "." in resolved:
            mod, _, last = resolved.rpartition(".")
            gid = (mod, last)
        else:
            gid = (rec.name, resolved)
        return gid if gid in self.globals else None

    def _walk_module(self, rec: ModuleRec) -> None:
        for info in rec.model.functions:
            fn = self.fn_of(rec.name, info.node)
            body = info.node.body if not isinstance(info.node, ast.Lambda) \
                else [ast.Expr(info.node.body)]
            self._walk_body(rec, fn, body)
        # module-level code (everything outside function bodies)
        mod_fn = self.module_nodes[rec.name]
        self._walk_body(rec, mod_fn, rec.tree.body, module_level=True)

    def _walk_body(self, rec: ModuleRec, fn: FnNode, body,
                   module_level: bool = False) -> None:
        held: List[Tuple] = []

        def attr_target(expr) -> Optional[Tuple]:
            # self.X inside a class method -> class-attr id
            if isinstance(expr, ast.Attribute) \
                    and isinstance(expr.value, ast.Name) \
                    and expr.value.id == "self" and fn.cls is not None:
                return ("attr", fn.module, fn.cls, expr.attr)
            if isinstance(expr, ast.Name):
                gid = self._global_id(rec, expr)
                if gid is not None:
                    return ("global",) + gid
            return None

        def record_write(target: Optional[Tuple], line: int,
                         kind: str) -> None:
            if target is not None:
                fn.writes.append(Access(
                    target, fn, line, kind, frozenset(held)))

        def visit(node) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return  # separate FnNode, walked on its own
            if isinstance(node, ast.ClassDef):
                if module_level:
                    for child in node.body:
                        visit(child)
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                acquired = []
                for item in node.items:
                    visit(item.context_expr)
                    lid = self._lock_id(rec, fn.cls, item.context_expr)
                    if lid is not None:
                        acquired.append(lid)
                held.extend(acquired)
                for st in node.body:
                    visit(st)
                if acquired:
                    del held[len(held) - len(acquired):]
                return
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Subscript):
                        record_write(attr_target(tgt.value),
                                     node.lineno, "subscript")
                visit(node.value)
                for tgt in node.targets:
                    visit(tgt)
                return
            if isinstance(node, ast.AugAssign):
                tgt = node.target
                if isinstance(tgt, ast.Subscript):
                    record_write(attr_target(tgt.value),
                                 node.lineno, "subscript")
                else:
                    record_write(attr_target(tgt), node.lineno, "aug")
                    # `x += 1` READS x before storing (the lost-update
                    # half of the race) even though ast marks the
                    # target ctx=Store — count the read explicitly
                    if isinstance(tgt, ast.Attribute):
                        fn.attr_reads.add(tgt.attr)
                    elif isinstance(tgt, ast.Name):
                        gid = self._global_id(rec, tgt)
                        if gid is not None:
                            fn.global_reads.add(gid)
                visit(node.value)
                visit(tgt)
                return
            if isinstance(node, ast.Delete):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Subscript):
                        record_write(attr_target(tgt.value),
                                     node.lineno, "del")
                    visit(tgt)
                return
            if isinstance(node, ast.Call):
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr in MUTATORS:
                    record_write(attr_target(node.func.value),
                                 node.lineno, "mutcall")
                callees, precise = self._resolve_targets(
                    rec, fn.cls, node.func)
                site_held = frozenset(held)
                for callee in callees:
                    fn.calls.append(CallSite(
                        callee, node.lineno, site_held, precise))
                for child in ast.iter_child_nodes(node):
                    visit(child)
                return
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.ctx, ast.Load):
                fn.attr_reads.add(node.attr)
                if isinstance(node.value, ast.Name) \
                        and node.value.id == "self" \
                        and fn.cls is not None:
                    fn.self_reads.append(Access(
                        ("attr", fn.module, fn.cls, node.attr),
                        fn, node.lineno, "read", frozenset(held)))
            elif isinstance(node, ast.Name) \
                    and isinstance(node.ctx, ast.Load):
                gid = self._global_id(rec, node)
                if gid is not None:
                    fn.global_reads.add(gid)
            for child in ast.iter_child_nodes(node):
                visit(child)

        for st in body:
            visit(st)

    # ---- held-lock fixpoint ---------------------------------------------

    def _propagate_held(self) -> None:
        """held(f) = ∩ over every call site of f of (locks at the site
        ∪ caller's own held set): a lock counts as held in f only when
        EVERY path into f holds it. Entry targets and uncalled
        functions pin at ∅ (someone outside the graph can call them)."""
        callers: Dict[Tuple, List[Tuple[FnNode, CallSite]]] = {}
        for fn in self.fns:
            for cs in fn.calls:
                callers.setdefault(cs.callee.key, []).append((fn, cs))
        entry_keys = {e.fn.key for e in self.entries}
        # Optimistic fixpoint: called, non-entry functions start at ⊤
        # (represented by None — "every lock") and only shrink; entry
        # targets and uncalled functions pin at ∅ (anything outside the
        # graph may invoke them holding nothing).
        held: Dict[Tuple, Optional[Set[Tuple]]] = {}
        for fn in self.fns:
            if fn.key in entry_keys or fn.key not in callers:
                held[fn.key] = set()
            else:
                held[fn.key] = None
        for _ in range(40):
            changed = False
            for fn in self.fns:
                sites = callers.get(fn.key)
                if not sites or fn.key in entry_keys:
                    continue
                acc: Optional[Set[Tuple]] = None  # ⊤ until constrained
                for caller, cs in sites:
                    ch = held[caller.key]
                    if ch is None:
                        continue  # ⊤ caller: site = ⊤, no constraint
                    site = set(cs.held) | ch
                    acc = site if acc is None else (acc & site)
                if acc is not None and held[fn.key] != acc:
                    held[fn.key] = acc
                    changed = True
            if not changed:
                break
        for fn in self.fns:
            fn.held = held.get(fn.key) or set()

    # ---- reachability ----------------------------------------------------

    def _compute_reachability(self) -> None:
        self._thread_witness: Dict[Tuple, str] = {}
        hard_targets = {e.fn.key for e in self.entries
                        if e.kind in ("thread", "executor", "signal",
                                      "http")}

        def bfs(seeds: List[Tuple[FnNode, str]],
                witness: Dict[Tuple, str]) -> Set[Tuple]:
            seen: Set[Tuple] = set()
            queue = list(seeds)
            while queue:
                fn, via = queue.pop(0)
                if fn.key in seen:
                    continue
                seen.add(fn.key)
                witness.setdefault(fn.key, via)
                for cs in fn.calls:
                    if cs.callee.key not in seen:
                        queue.append((cs.callee, via))
            return seen

        self._thread_set = bfs(
            [(e.fn, f"{e.kind}:{e.fn.label()}") for e in self.entries],
            self._thread_witness)
        main_seeds = [(fn, "") for fn in self.fns
                      if fn.key not in hard_targets]
        self._main_set = bfs(main_seeds, {})

    def thread_reachable(self, fn: FnNode) -> bool:
        return fn.key in self._thread_set

    def main_reachable(self, fn: FnNode) -> bool:
        return fn.key in self._main_set

    def entry_witness(self, fn: FnNode) -> str:
        return self._thread_witness.get(fn.key, "")

    def signal_entries(self) -> List[Entry]:
        return [e for e in self.entries if e.kind == "signal"]

    def closure(self, roots: Iterable[FnNode], max_fns: int = 400,
                max_depth: Optional[int] = None) -> List[FnNode]:
        """Call-graph closure from `roots` (bounded; the concurrency
        rules scan it for unsafe operations). `max_depth` caps the hop
        count from a root — JGL010 uses a small cap so findings anchor
        near the handler instead of deep inside shared sinks every
        caller funnels through."""
        seen: Set[Tuple] = set()
        out: List[FnNode] = []
        queue = [(fn, 0) for fn in roots]
        while queue and len(out) < max_fns:
            fn, depth = queue.pop(0)
            if fn.key in seen:
                continue
            seen.add(fn.key)
            out.append(fn)
            if max_depth is not None and depth >= max_depth:
                continue
            for cs in fn.calls:
                if cs.callee.key not in seen:
                    queue.append((cs.callee, depth + 1))
        return out

    def direct_call_lines(self, fn: FnNode) -> List[int]:
        """Lines where `fn` is CALLED (not spawned) anywhere in the
        project — the JGL011 'work re-runs at a synchronous barrier'
        exemption."""
        out = []
        for caller in self.fns:
            for cs in caller.calls:
                if cs.callee.key == fn.key:
                    out.append(cs.line)
        return out

    # ---- shared-state aggregation (JGL009 inputs) ------------------------

    def shared_writes(self) -> Dict[Tuple, List[Access]]:
        """All collected writes grouped by target id."""
        out: Dict[Tuple, List[Access]] = {}
        for fn in self.fns:
            for w in fn.writes:
                out.setdefault(w.target, []).append(w)
        return out

    def attr_readers(self, name: str) -> List[FnNode]:
        return [fn for fn in self.fns if name in fn.attr_reads]

    def self_reads_of(self, target: Tuple) -> List[Access]:
        """Same-class `self.X` reads of one class-attr target — the
        only reads precise enough to flag (cross-object attribute
        reads are name-matched and would misfire across classes)."""
        out: List[Access] = []
        for fn in self.fns:
            for r in fn.self_reads:
                if r.target == target:
                    out.append(r)
        return out

    def global_readers(self, gid: Tuple[str, str]) -> List[FnNode]:
        return [fn for fn in self.fns if gid in fn.global_reads]

    # ---- cross-module traced propagation ---------------------------------

    def propagate_traced(self) -> None:
        """Traced (jit/scan/vmap) reachability across import-resolved
        edges: a traced function calling into another module marks the
        callee traced there and re-propagates module-locally, to a
        fixpoint. Name-matched edges are excluded — they are good
        enough for conservative thread reachability but far too blunt
        to taint tracing with."""
        for _ in range(20):
            seeds: Dict[str, Set[str]] = {}
            for fn in self.fns:
                if fn.info is None or not fn.info.traced:
                    continue
                for cs in fn.calls:
                    callee = cs.callee
                    if (cs.precise and callee.info is not None
                            and callee.module != fn.module
                            and not callee.info.traced):
                        seeds.setdefault(callee.module, set()).add(
                            callee.info.name)
            if not seeds:
                return
            changed = False
            for mod, names in seeds.items():
                if self.modules[mod].model.seed_traced(names):
                    changed = True
            if not changed:
                return
