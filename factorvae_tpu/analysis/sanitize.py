"""Runtime lock-order sanitizer: the dynamic half of the concurrency
gate (graftlint JGL009-011 are the static half).

Static analysis can prove a write is unguarded; it cannot prove two
subsystems' locks are always taken in one global order — that property
only exists at runtime, the first time the subsystems COMPOSE (a
daemon tick holding the tick lock cold-starts a registry entry that
verifies a checkpoint that logs to the timeline...). `LockOrderRecorder`
wraps `threading.Lock` / `threading.RLock` construction while
installed, keeps a per-thread stack of held wrapped locks, and records
every *held-while-acquiring* pair as an edge in a directed graph keyed
by lock CREATION SITE (all instances born at `registry.py:210` are one
order class). A cycle in that graph is a lock-order inversion: two
threads interleaving those acquisition paths can deadlock, even if no
test run ever actually deadlocked. `check()` fails loudly with the
cycle and a witness (thread + acquire site) per edge.

Usage (the tier-1 fixture in tests/test_sanitize.py drives the
Checkpointer + Timeline + metrics + registry + chaos lock set through
exactly this):

    rec = LockOrderRecorder(only=("factorvae_tpu/",))
    with rec:                      # patches the lock factories
        ...build loggers/checkpointers/registries, run the workload...
    rec.check()                    # raises LockOrderError on a cycle

Notes and scope:

- Only locks CREATED while the recorder is installed are wrapped
  (construction-time patch, not acquisition-time). `only` filters by
  the creation site's filename, so stdlib-internal locks (threading's
  own Conditions, orbax's executors) stay native and unrecorded.
  Locks born BEFORE install — module-level locks like the watchdog's
  counter lock, created at import — are invisible to the patch;
  fixtures bring them in explicitly with `adopt(module, "_LOCK")`,
  which wraps the existing lock in place and restores it on
  uninstall.
- Same-site edges (two instances of one class nested) are excluded
  from cycle detection: instance-order within a class needs its own
  discipline and would otherwise self-cycle on the first fleet of
  per-seed Checkpointers.
- RLock re-entry (same instance already held by this thread) records
  no edge — re-acquisition is not an ordering event.
- `make_lock(label)` hands out a wrapped lock directly (no patching) —
  the seeded-inversion tests use it for deterministic labels.
- The wrapper tolerates releases it never saw (Condition's
  `_release_save` bypasses `release()`): the held stack is pruned by
  identity, never assumed balanced.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["LockOrderError", "LockOrderRecorder", "RecordedLock"]

_THIS_FILE = os.path.abspath(__file__)


class LockOrderError(AssertionError):
    """A lock-order inversion (cycle in the held-while-acquiring
    graph) was recorded; the message carries the cycle and witnesses."""


def _acquire_site() -> str:
    """file:line of the frame that called into the lock wrapper."""
    f = sys._getframe(1)
    while f is not None and os.path.abspath(
            f.f_code.co_filename) == _THIS_FILE:
        f = f.f_back
    if f is None:
        return "<unknown>"
    return f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"


class RecordedLock:
    """Transparent proxy over a real lock that reports acquisition
    order to its recorder. Same acquire/release/context-manager
    surface; everything else delegates to the wrapped lock."""

    def __init__(self, recorder: "LockOrderRecorder", inner,
                 label: str, reentrant: bool):
        self._recorder = recorder
        self._inner = inner
        self.label = label
        self.reentrant = reentrant

    def acquire(self, *args, **kwargs):
        ok = self._inner.acquire(*args, **kwargs)
        if ok:
            self._recorder._acquired(self)
        return ok

    def release(self):
        self._recorder._released(self)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()

    def __getattr__(self, attr):
        # Condition() introspects _is_owned/_release_save/... on RLocks
        return getattr(self._inner, attr)

    def __repr__(self):
        return f"<RecordedLock {self.label}>"


class LockOrderRecorder:
    def __init__(self, only: Optional[Sequence[str]] = None):
        #: substrings a creation site's path must contain to be
        #: wrapped; empty = wrap every lock created while installed
        self.only = tuple(p.replace(os.sep, "/") for p in (only or ()))
        # (held_label, acquired_label) -> witness
        self._edges: Dict[Tuple[str, str], dict] = {}
        self._tls = threading.local()
        self._meta = threading.Lock()   # guards _edges (a REAL lock)
        self._orig: Optional[tuple] = None
        # (owner, attr, original) for adopt()ed pre-existing locks
        self._adopted: List[tuple] = []

    # ---- construction-time patch ----------------------------------------

    def install(self) -> "LockOrderRecorder":
        if self._orig is not None:
            return self
        self._orig = (threading.Lock, threading.RLock)
        rec = self

        def factory(orig, reentrant):
            def patched():
                frame = sys._getframe(1)
                fname = frame.f_code.co_filename.replace(os.sep, "/")
                if rec.only and not any(p in fname for p in rec.only):
                    return orig()
                label = (f"{os.path.basename(fname)}:"
                         f"{frame.f_lineno}")
                return RecordedLock(rec, orig(), label, reentrant)
            return patched

        threading.Lock = factory(self._orig[0], False)
        threading.RLock = factory(self._orig[1], True)
        return self

    def uninstall(self) -> None:
        if self._orig is not None:
            threading.Lock, threading.RLock = self._orig
            self._orig = None
        while self._adopted:
            owner, attr, original = self._adopted.pop()
            setattr(owner, attr, original)

    def __enter__(self) -> "LockOrderRecorder":
        return self.install()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.uninstall()

    def make_lock(self, label: str,
                  reentrant: bool = False) -> RecordedLock:
        """A wrapped lock with an explicit label, without patching the
        factories — deterministic handles for tests."""
        orig = self._orig or (threading.Lock, threading.RLock)
        inner = orig[1]() if reentrant else orig[0]()
        return RecordedLock(self, inner, label, reentrant)

    def adopt(self, owner, attr: str,
              label: Optional[str] = None) -> RecordedLock:
        """Wrap an ALREADY-CONSTRUCTED lock bound at `owner.attr` (a
        module global like watchdog._COUNTS_LOCK, or an instance
        attribute). The construction-time patch cannot see locks
        created before install() — module-level locks are born at
        import — so fixtures adopt them explicitly: the existing inner
        lock is wrapped in place (every use site that goes through the
        name sees the recorder) and restored on uninstall()."""
        inner = getattr(owner, attr)
        if isinstance(inner, RecordedLock):
            return inner
        name = getattr(owner, "__name__", type(owner).__name__)
        wrapped = RecordedLock(self, inner, label or f"{name}.{attr}",
                               reentrant=not hasattr(inner, "locked"))
        setattr(owner, attr, wrapped)
        self._adopted.append((owner, attr, inner))
        return wrapped

    # ---- acquisition tracking -------------------------------------------

    def _stack(self) -> List[RecordedLock]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = []
            self._tls.stack = st
        return st

    def _acquired(self, lock: RecordedLock) -> None:
        stack = self._stack()
        if any(h is lock for h in stack):
            # re-entry on the same instance: not an ordering event,
            # but keep the stack balanced for the matching release
            stack.append(lock)
            return
        site = _acquire_site()
        new_edges = []
        seen_labels = set()
        for held in stack:
            if held.label == lock.label or held.label in seen_labels:
                continue  # same order class / duplicate held label
            seen_labels.add(held.label)
            new_edges.append((held.label, lock.label))
        if new_edges:
            thread = threading.current_thread().name
            with self._meta:
                for edge in new_edges:
                    self._edges.setdefault(edge, {
                        "thread": thread, "site": site})
        stack.append(lock)

    def _released(self, lock: RecordedLock) -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is lock:
                del stack[i]
                return
        # a release we never saw acquired (Condition internals):
        # nothing to prune, nothing to complain about

    # ---- analysis --------------------------------------------------------

    def edges(self) -> Dict[Tuple[str, str], dict]:
        with self._meta:
            return dict(self._edges)

    def cycles(self) -> List[List[str]]:
        """Every distinct cycle (as a label path a -> b -> ... -> a)
        in the held-while-acquiring graph."""
        edges = self.edges()
        adj: Dict[str, List[str]] = {}
        for a, b in edges:
            adj.setdefault(a, []).append(b)
        for succs in adj.values():
            succs.sort()
        out: List[List[str]] = []
        seen_cycles = set()

        def dfs(node: str, path: List[str], on_path: set) -> None:
            for nxt in adj.get(node, ()):
                if nxt in on_path:
                    cycle = path[path.index(nxt):] + [nxt]
                    # dedup by ROTATION-normalized edge sequence, not
                    # node set: A->B->C->A and A->C->B->A over the same
                    # three locks are two distinct inversions and must
                    # both be reported (each names different edges to
                    # fix)
                    seq = tuple(cycle[:-1])
                    key = min(seq[i:] + seq[:i]
                              for i in range(len(seq)))
                    if key not in seen_cycles:
                        seen_cycles.add(key)
                        out.append(cycle)
                    continue
                on_path.add(nxt)
                dfs(nxt, path + [nxt], on_path)
                on_path.discard(nxt)

        for start in sorted(adj):
            dfs(start, [start], {start})
        return out

    def report(self) -> str:
        """Human-readable inversion report: each cycle with the
        witness (thread + acquire site) for every edge on it."""
        cycles = self.cycles()
        if not cycles:
            return "lock-order sanitizer: no inversions " \
                   f"({len(self.edges())} ordered pair(s) observed)"
        edges = self.edges()
        lines = [f"lock-order inversion: {len(cycles)} cycle(s) in the "
                 "held-while-acquiring graph"]
        for cycle in cycles:
            lines.append("  cycle: " + " -> ".join(cycle))
            for a, b in zip(cycle, cycle[1:]):
                w = edges.get((a, b), {})
                lines.append(
                    f"    {a} held while acquiring {b}  "
                    f"[thread {w.get('thread', '?')}, "
                    f"at {w.get('site', '?')}]")
        lines.append(
            "  two threads interleaving these acquisition paths "
            "deadlock; pick one global order and take the locks in it")
        return "\n".join(lines)

    def check(self) -> None:
        """Raise `LockOrderError` with the full report if any cycle
        was recorded."""
        if self.cycles():
            raise LockOrderError(self.report())
