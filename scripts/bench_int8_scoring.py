"""Race the serving-precision ladder (f32 vs int8, optionally bf16) at
the flagship shape on the current backend — a THIN SHIM over the
serve/plan precision path (serve/registry.py; the same rungs
`scripts/autotune_plan.py --serve` races into the plan table and the
scoring daemon serves).

Prints one JSON line per variant plus a summary, and ALWAYS writes the
`BENCH_INT8_SCORING.json` artifact with the resolved `plan` block and
the measuring process's `run_meta` (the bench_reference_cpu.py
convention), so the perf ledger can track the series
(`python -m factorvae_tpu.obs.ledger --backfill` picks it up; the
artifact IS a ledger payload: metric/value/unit at top level).

Usage: python scripts/bench_int8_scoring.py [--days 256] [--reps 5]
           [--bf16] [--out BENCH_INT8_SCORING.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--days", type=int, default=256)
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--chunk", type=int, default=32)
    ap.add_argument("--stocks", type=int, default=356)
    ap.add_argument("--bf16", action="store_true",
                    help="race the bfloat16 rung too (default: the "
                         "historical f32-vs-int8 A/B)")
    ap.add_argument("--out", default=None,
                    help="artifact path (default: BENCH_INT8_SCORING."
                         "json at the repo root)")
    args = ap.parse_args()

    import jax
    import numpy as np

    from factorvae_tpu import plan as planlib
    from factorvae_tpu.config import (
        Config,
        DataConfig,
        ModelConfig,
        TrainConfig,
    )
    from factorvae_tpu.data import PanelDataset, synthetic_panel_dense
    from factorvae_tpu.models.factorvae import day_prediction
    from factorvae_tpu.ops.quant import quantize_params, tree_nbytes
    from factorvae_tpu.serve.registry import ModelRegistry
    from factorvae_tpu.utils.logging import run_meta

    platform = jax.devices()[0].platform
    cfg = Config(
        model=ModelConfig(num_features=158, hidden_size=64, num_factors=96,
                          num_portfolios=128, seq_len=20),
        data=DataConfig(seq_len=20, start_time=None, fit_end_time=None,
                        val_start_time=None, val_end_time=None),
        train=TrainConfig(seed=0),
    )
    ds = PanelDataset(
        synthetic_panel_dense(num_days=args.days,
                              num_instruments=args.stocks,
                              num_features=158),
        seq_len=20, pad_multiple=8,
    )
    import jax.numpy as jnp

    model = day_prediction(cfg.model, stochastic=False)
    x0 = jnp.zeros((1, ds.n_max, 20, 158), jnp.float32)
    m0 = jnp.ones((1, ds.n_max), bool)
    params = model.init(
        {"params": jax.random.PRNGKey(0), "sample": jax.random.PRNGKey(1)},
        x0, m0)
    days = ds.split_days(None, None)

    # The planner's decision block for this (platform, shape) — the
    # provenance every tracked bench row carries.
    shape = planlib.shape_of(cfg, args.stocks)
    plan = planlib.plan_for(shape, platform=platform)
    plan_block = plan.describe(shape, platform=platform)

    f32_bytes = tree_nbytes(params)
    i8_bytes = tree_nbytes(quantize_params(params))

    reg = ModelRegistry()
    ladder = ["f32"] + (["bf16"] if args.bf16 else []) + ["int8"]
    precision_of = {"f32": "float32", "bf16": "bfloat16", "int8": "int8"}
    results: dict = {}
    variants: dict = {}
    for name in ladder:
        key = reg.register_params(params, cfg,
                                  precision=precision_of[name])
        # compile + warm through the registry's scoring entry point —
        # the exact request path the daemon serves.
        reg.score(key, ds, days[: args.chunk], stochastic=False,
                  chunk=args.chunk)
        times = []
        for _ in range(args.reps):
            t0 = time.perf_counter()
            out = reg.score(key, ds, days, stochastic=False,
                            chunk=args.chunk)
            times.append(time.perf_counter() - t0)
        med = float(np.median(times))
        dps = len(days) / med
        results[name] = dps
        variants[name] = {
            "variant": name, "platform": platform, "days": len(days),
            "seconds": round(med, 4), "days_per_sec": round(dps, 1),
            "windows_per_sec": round(dps * ds.n_max, 1),
            "param_bytes": i8_bytes if name == "int8" else f32_bytes,
            "finite": bool(np.isfinite(out).any()),
        }
        print(json.dumps(variants[name]))
    summary = {
        "summary": "int8_vs_f32_scoring",
        "speedup": round(results["int8"] / results["f32"], 3),
        "bytes_ratio": round(f32_bytes / i8_bytes, 2),
    }
    print(json.dumps(summary))

    # Ledger-trackable artifact (always written): the int8 rung's
    # windows/sec is the headline — the rung this script exists to
    # watch — with every variant, the plan block and the measuring
    # rig's run_meta alongside.
    artifact = {
        "metric": f"serve_int8_scoring_N{args.stocks}_d{args.days}",
        "value": round(results["int8"] * ds.n_max, 1),
        "unit": "windows/sec",
        "platform": platform,
        "vs_baseline": None,
        "speedup_vs_f32": summary["speedup"],
        "bytes_ratio": summary["bytes_ratio"],
        "variants": variants,
        "plan": plan_block,
        "run_meta": run_meta(config=cfg.to_dict()),
    }
    out_path = args.out or os.path.join(REPO, "BENCH_INT8_SCORING.json")
    try:
        with open(out_path, "w") as fh:
            json.dump(artifact, fh, indent=1)
            fh.write("\n")
    except OSError as e:  # read-only checkout: report, don't crash
        print(f"[bench_int8] artifact not written: {e}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
