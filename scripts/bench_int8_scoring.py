"""Race f32 vs int8-weight scoring at flagship shapes on the current
backend. Prints one JSON line per variant plus a summary.

Usage: python scripts/bench_int8_scoring.py [--days 256] [--reps 5]

The scoring path (eval/predict.predict_panel) is chunked jitted
day-batched inference; the int8 variant stores weights in HBM as
per-channel int8 and dequantizes in the compiled program (ops/quant.py).
At FactorVAE sizes the win to measure is parameter-byte residency and
any bandwidth-bound speedup; fidelity is tested in tests/test_quant.py.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, ".")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--days", type=int, default=256)
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--chunk", type=int, default=32)
    args = ap.parse_args()

    import jax
    import numpy as np

    from factorvae_tpu.config import Config, DataConfig, ModelConfig, TrainConfig
    from factorvae_tpu.data import PanelDataset, synthetic_panel_dense
    from factorvae_tpu.eval.predict import predict_panel
    from factorvae_tpu.ops.quant import quantize_params, tree_nbytes

    platform = jax.devices()[0].platform
    cfg = Config(
        model=ModelConfig(num_features=158, hidden_size=64, num_factors=96,
                          num_portfolios=128, seq_len=20),
        data=DataConfig(seq_len=20, start_time=None, fit_end_time=None,
                        val_start_time=None, val_end_time=None),
        train=TrainConfig(seed=0),
    )
    ds = PanelDataset(
        synthetic_panel_dense(num_days=args.days, num_instruments=356,
                              num_features=158),
        seq_len=20, pad_multiple=8,
    )
    import jax.numpy as jnp

    from factorvae_tpu.models.factorvae import day_prediction

    model = day_prediction(cfg.model, stochastic=False)
    x0 = jnp.zeros((1, ds.n_max, 20, 158), jnp.float32)
    m0 = jnp.ones((1, ds.n_max), bool)
    params = model.init(
        {"params": jax.random.PRNGKey(0), "sample": jax.random.PRNGKey(1)},
        x0, m0)
    days = ds.split_days(None, None)

    f32_bytes = tree_nbytes(params)
    i8_bytes = tree_nbytes(quantize_params(params))

    results = {}
    for name, kw in [("f32", {}), ("int8", {"int8": True})]:
        # compile + warm
        predict_panel(params, cfg, ds, days[: args.chunk], stochastic=False,
                      chunk=args.chunk, **kw)
        times = []
        for _ in range(args.reps):
            t0 = time.perf_counter()
            out = predict_panel(params, cfg, ds, days, stochastic=False,
                                chunk=args.chunk, **kw)
            times.append(time.perf_counter() - t0)
        med = float(np.median(times))
        dps = len(days) / med
        results[name] = dps
        print(json.dumps({
            "variant": name, "platform": platform, "days": len(days),
            "seconds": round(med, 4), "days_per_sec": round(dps, 1),
            "windows_per_sec": round(dps * ds.n_max, 1),
            "param_bytes": i8_bytes if name == "int8" else f32_bytes,
            "finite": bool(np.isfinite(out).any()),
        }))
    print(json.dumps({
        "summary": "int8_vs_f32_scoring",
        "speedup": round(results["int8"] / results["f32"], 3),
        "bytes_ratio": round(f32_bytes / i8_bytes, 2),
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
