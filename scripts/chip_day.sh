#!/bin/bash
# One-command TPU capture day (VERDICT r3 next-steps #1, #3, #7, plus the
# on-chip k60 parity sweep #2). The relay has been down for most of two
# rounds; when it returns it may not stay up — so every capture step is
# bounded, ordered by evidentiary value, persists its artifact
# immediately, and failures don't stop the sequence.
#
# Usage:   bash scripts/chip_day.sh [outdir]   (default: repo root)
# Outputs: BENCH_r05_tpu.json + BENCH_TPU_CAPTURE.json (bench.py side
#          effect), BENCH_DPS_SWEEP_r05.jsonl, RACE_KERNELS_TPU_r05.json,
#          INT8_RACE_r05.json, TRACE_r05/ + TRACE_SUMMARY_r05.md,
#          PARITY_RUN_r05.json — all under [outdir]; CHIP_DAY.log is the
#          session transcript.

set -u
cd "$(dirname "$0")/.."
OUT="${1:-.}"
mkdir -p "$OUT"
LOG="$OUT/CHIP_DAY.log"
# bench.py writes its persisted chip capture to the repo root by default;
# keep it with the rest of the day's artifacts.
export BENCH_CAPTURE_PATH="$OUT/BENCH_TPU_CAPTURE.json"
say() { echo "[chip_day $(date +%H:%M:%S)] $*" | tee -a "$LOG"; }

say "probe: bounded jax.devices() check"
if ! timeout 120 python -u -c "
import jax
d = jax.devices()[0]
assert d.platform != 'cpu', d
print('platform:', d.platform)
" >>"$LOG" 2>&1; then
  say "ABORT: no accelerator (probe hung or cpu-only); nothing captured"
  exit 1
fi

say "1/6 flagship bench (flattened default) -> BENCH_r05_tpu.json"
timeout 1800 python bench.py >"$OUT/BENCH_r05_tpu.json" 2>>"$LOG" \
  && say "bench ok: $(cat "$OUT/BENCH_r05_tpu.json")" \
  || say "bench FAILED (rc=$?)"

say "2/6 days_per_step sweep -> BENCH_DPS_SWEEP_r05.jsonl"
: >"$OUT/BENCH_DPS_SWEEP_r05.jsonl"
for dps in 4 8 16 32; do
  BENCH_DAYS_PER_STEP=$dps timeout 1500 python bench.py \
    >>"$OUT/BENCH_DPS_SWEEP_r05.jsonl" 2>>"$LOG" \
    && say "dps=$dps ok" || say "dps=$dps FAILED"
done

say "2b/6 flatten_days A/B (r3 thesis) -> appended to BENCH_DPS_SWEEP_r05.jsonl"
BENCH_FLATTEN=0 timeout 1500 python bench.py \
  >>"$OUT/BENCH_DPS_SWEEP_r05.jsonl" 2>>"$LOG" \
  && say "flatten=0 ok" || say "flatten=0 FAILED"

say "2c/6 preset-scale benches (csi800 N=1024, alpha360 C=360/T=60)"
BENCH_STOCKS=1020 BENCH_HIDDEN=60 BENCH_FACTORS=60 timeout 1500 \
  python bench.py >>"$OUT/BENCH_DPS_SWEEP_r05.jsonl" 2>>"$LOG" \
  && say "csi800-scale ok" || say "csi800-scale FAILED"
BENCH_FEATURES=360 BENCH_SEQ_LEN=60 BENCH_HIDDEN=60 BENCH_FACTORS=60 \
  timeout 1500 python bench.py >>"$OUT/BENCH_DPS_SWEEP_r05.jsonl" 2>>"$LOG" \
  && say "alpha360-scale ok" || say "alpha360-scale FAILED"

say "3/6 kernel race at flattened shapes -> RACE_KERNELS_TPU_r05.json"
timeout 3600 python scripts/race_kernels.py \
  --out "$OUT/RACE_KERNELS_TPU_r05.json" >>"$LOG" 2>&1 \
  && say "race ok" || say "race FAILED (rc=$?)"

say "4/6 int8 scoring race -> INT8_RACE_r05.json"
timeout 1200 python scripts/bench_int8_scoring.py \
  >"$OUT/INT8_RACE_r05.json" 2>>"$LOG" \
  && say "int8 ok" || say "int8 FAILED (rc=$?)"

say "5/6 profiler trace of flagship training -> TRACE_SUMMARY_r05.md"
rm -rf "$OUT/TRACE_r05"; mkdir -p /tmp/chipday
timeout 900 python - >>"$LOG" 2>&1 <<'EOF'
from factorvae_tpu.data import synthetic_frame
synthetic_frame(num_days=80, num_instruments=356, num_features=158,
                missing_prob=0.02, seed=3).to_pickle('/tmp/chipday/panel.pkl')
EOF
timeout 1800 python -m factorvae_tpu.cli \
  --dataset /tmp/chipday/panel.pkl --num_epochs 3 \
  --start_time 2020-01-01 --fit_end_time 2020-04-10 \
  --val_start_time 2020-04-13 --val_end_time 2020-04-21 \
  --days_per_step 8 --save_dir /tmp/chipday/models \
  --score_start 2020-04-13 --score_end 2020-04-21 \
  --score_dir /tmp/chipday/scores \
  --profile "$OUT/TRACE_r05" >>"$LOG" 2>&1 \
  && say "trace captured" || say "trace FAILED (rc=$?)"
timeout 600 python -m factorvae_tpu.utils.trace_summary "$OUT/TRACE_r05" \
  >"$OUT/TRACE_SUMMARY_r05.md" 2>>"$LOG" \
  && say "trace summarized" || say "trace summary FAILED"

say "6/6 k60 parity sweep ON CHIP (full protocol) -> PARITY_RUN_r05.json"
timeout 14400 python scripts/parity_k60_sweep.py \
  --epochs 50 --seeds 8 --out "$OUT/PARITY_RUN_r05.json" >>"$LOG" 2>&1 \
  && say "parity sweep ok" || say "parity sweep FAILED/partial (rc=$?)"

say "chip day complete; artifacts in $OUT"
