"""SNR ceiling of the k60 proxy-parity protocol (VERDICT r3 next-#2).

The proxy panel (scripts/parity_protocol.py:77-119) plants the z-scored
real reference K=60 scores as the latent alpha, embeds them in the 158
features as  x = FS * alpha * w + N(0,1)  with FS=2.0 and |w|~=1, and
labels  y = LS * (s * alpha + sqrt(1-s^2) * eps)  with s=0.08. The
reference row of every parity table scores alpha ITSELF (Rank-IC
~0.0794), but no model sees alpha — only the noisy features. This
script measures what fraction of the reference Rank-IC is recoverable
AT ALL from the features, independent of model class:

1. oracle-w:   alpha_hat = x . w / (FS |w|^2)  — the minimum-variance
   linear estimate given the TRUE embedding direction. Analytically
   corr(alpha_hat, alpha) = FS|w| / sqrt(FS^2|w|^2 + 1); at the seed-0
   panel's realized |w| = 1.13 that is 0.915, so even a perfect learner
   cannot exceed ~91% recovery on this protocol.
2. ridge-w:    w learned by ridge regression of the label on the
   last-day features over the 800-day training prefix — the realistic
   linear ceiling (estimation error included).
3. reference:  alpha scored directly (the 100% row).

Any model recovery quoted against the reference row should be read
against ceiling (1): e.g. a sweep mean at 70% of the reference is 79%
of what the features contain. Output: SNR_CEILING_r04.json.

Usage: python scripts/snr_ceiling.py [--out SNR_CEILING_r04.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np
import pandas as pd

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from parity_protocol import (  # noqa: E402
    ALPHA_SOURCE,
    FEATURE_STRENGTH,
    SIGNAL,
    build_proxy_panel,
    load_ref_scores,
)


def daily_spearman(pred: np.ndarray, lab: np.ndarray,
                   valid: np.ndarray) -> float:
    """Mean per-day Spearman via the library's vectorized rank-IC
    (ops.stats.rank_ic_series — the same average-rank semantics every
    parity number uses; no per-day host loop)."""
    import jax.numpy as jnp

    from factorvae_tpu.ops.stats import rank_ic_series

    ics = np.asarray(rank_ic_series(
        jnp.asarray(pred, jnp.float32), jnp.asarray(lab, jnp.float32),
        jnp.asarray(valid)))
    ics = ics[valid.sum(axis=1) >= 3]
    return float(np.nanmean(ics))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--scores_dir", default="/root/reference/scores")
    ap.add_argument("--out", default="SNR_CEILING_r04.json")
    ap.add_argument("--ridge", type=float, default=1.0)
    args = ap.parse_args(argv)

    ref = load_ref_scores(args.scores_dir)
    panel, prefix_dates, window_dates = build_proxy_panel(ref)
    p = len(prefix_dates)

    # (D, N, C) features, (D, N) labels, with the panel's (N, D, C+1)
    # layout transposed to day-major.
    vals = np.transpose(panel.values, (1, 0, 2))
    feats, labels = vals[..., :-1], vals[..., -1]
    valid = panel.valid & np.isfinite(labels)

    # Reconstruct the generator's embedding direction exactly as
    # build_proxy_panel drew it (same seed stream: alpha (n,d) first,
    # then w): the oracle needs the true w, not an approximation.
    rng = np.random.default_rng(0)
    n, d, c = len(panel.instruments), len(panel.dates), feats.shape[-1]
    rng.normal(size=(n, d))                      # alpha draw (discarded)
    w = (rng.normal(size=(c,)) / np.sqrt(c)).astype(np.float32)
    w_norm2 = float(w @ w)

    win = slice(p, d)
    wv = valid[win]

    out = {
        "protocol": "scripts/parity_protocol.py proxy panel",
        "alpha_source": ALPHA_SOURCE,
        "signal": SIGNAL,
        "feature_strength": FEATURE_STRENGTH,
        "w_norm": float(np.sqrt(w_norm2)),
        "analytic_alpha_corr_ceiling": float(
            FEATURE_STRENGTH * np.sqrt(w_norm2)
            / np.sqrt(FEATURE_STRENGTH ** 2 * w_norm2 + 1.0)),
    }

    # 3) reference row: alpha scored directly. Rebuild alpha from the
    # window features is impossible (that's the point) — recover it from
    # the reference scores exactly as the panel build planted them.
    from parity_protocol import zscore_by_day

    src = ref[ALPHA_SOURCE]["score"]
    z = zscore_by_day(src)
    date_pos = pd.Series(np.arange(d), index=panel.dates)
    inst_pos = pd.Series(np.arange(n), index=panel.instruments)
    di = date_pos[z.index.get_level_values(0)].to_numpy()
    ii = inst_pos[z.index.get_level_values(1)].to_numpy()
    alpha = np.full((d, n), np.nan, np.float32)
    alpha[di, ii] = z.to_numpy().astype(np.float32)
    out["reference_rank_ic"] = daily_spearman(
        np.nan_to_num(alpha[win]), labels[win], wv)

    # Cross-check the RNG-stream replay of w against an INDEPENDENT
    # re-derivation from the data: regressing the window features on the
    # known planted alpha recovers FS*w up to noise. A refactor of
    # build_proxy_panel's draw order/seed would silently corrupt the
    # replayed w; this guard turns that into a loud failure.
    aw = np.nan_to_num(alpha[win]) * wv
    w_check = (aw[..., None] * np.nan_to_num(feats[win])).sum((0, 1)) / \
        np.maximum((aw ** 2).sum(), 1e-9) / FEATURE_STRENGTH
    cos = float((w_check @ w)
                / (np.linalg.norm(w_check) * np.linalg.norm(w)))
    assert cos > 0.95, (
        f"replayed w diverges from data-derived w (cos={cos:.3f}); "
        "build_proxy_panel's RNG stream has changed — update the replay")
    out["w_replay_cos_check"] = cos

    # 1) oracle-w estimator on the window days.
    nanfeats = np.nan_to_num(feats)
    alpha_hat = nanfeats @ w / (FEATURE_STRENGTH * w_norm2)
    out["oracle_w_rank_ic"] = daily_spearman(alpha_hat[win], labels[win], wv)
    out["oracle_w_recovery"] = out["oracle_w_rank_ic"] / \
        out["reference_rank_ic"]

    # 2) ridge-learned w on the training prefix (last-day features only,
    # like the oracle — the extra T-1 window days carry no day-t alpha).
    tv = valid[:p]
    X = feats[:p][tv]
    y = labels[:p][tv]
    G = X.T @ X + args.ridge * np.eye(c, dtype=np.float64)
    w_hat = np.linalg.solve(G, X.T @ y)
    ridge_pred = nanfeats @ w_hat
    out["ridge_w_rank_ic"] = daily_spearman(ridge_pred[win], labels[win], wv)
    out["ridge_w_recovery"] = out["ridge_w_rank_ic"] / \
        out["reference_rank_ic"]
    out["ridge_w_cos_to_true_w"] = float(
        (w_hat @ w) / (np.linalg.norm(w_hat) * np.linalg.norm(w)))

    with open(args.out, "w") as fh:
        json.dump(out, fh, indent=1)
    print(json.dumps(out, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
