"""Scale demos: CSI800 (N=1024) and Alpha360 (C=360, T=60) end-to-end.

VERDICT r1 item 6: run the two big BASELINE.json configs (4: CSI800
K=60/H=60 with the cross-section padded to 1024; 5: Alpha360 features
C=360 at seq_len=60) end-to-end and measure throughput + device memory,
single-chip and under a stock-sharded mesh.

Notes on the mesh variant: the sandbox exposes ONE real TPU chip, so
`--mesh_stock 2` can only execute on the virtual CPU mesh — launch with
`JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8`
(or via tests' force_host_devices), where wall-clock on the 1-core host
is meaningless: the mesh run is a correctness/compile demonstration;
the sharding-payoff question needs real multi-chip wall-clock.
Single-chip numbers are real v5e measurements.

This intentionally repeats bench.py's warmup+timed-epochs methodology
(different metrics: HBM peak + compile time here, MFU/vs_baseline
there); if the timing protocol changes, change both.

Usage:
    python scripts/scale_demo.py [--config csi800|alpha360|both]
        [--days 64] [--epochs 2] [--mesh_stock N] [--out SCALE_DEMO.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SHAPES = {
    # BASELINE.json config 4: CSI800 universe, K=60/H=60, Alpha158
    "csi800": dict(num_features=158, seq_len=20, hidden=60, factors=60,
                   portfolios=128, stocks=800, max_stocks=1024),
    # BASELINE.json config 5: Alpha360 features, seq_len=60
    "alpha360": dict(num_features=360, seq_len=60, hidden=60, factors=60,
                     portfolios=128, stocks=300, max_stocks=None),
}


def run_config(name: str, days: int, epochs: int, days_per_step: int,
               bf16: bool, mesh_stock: int = 1) -> dict:
    import jax

    from factorvae_tpu.config import (
        Config, DataConfig, MeshConfig, ModelConfig, TrainConfig,
    )
    from factorvae_tpu.data import PanelDataset, synthetic_panel_dense
    from factorvae_tpu.parallel import make_mesh
    from factorvae_tpu.train import Trainer
    from factorvae_tpu.utils.logging import MetricsLogger

    s = SHAPES[name]
    cfg = Config(
        model=ModelConfig(
            num_features=s["num_features"], hidden_size=s["hidden"],
            num_factors=s["factors"], num_portfolios=s["portfolios"],
            seq_len=s["seq_len"],
            compute_dtype="bfloat16" if bf16 else "float32",
        ),
        data=DataConfig(seq_len=s["seq_len"], start_time=None,
                        fit_end_time=None, val_start_time=None,
                        val_end_time=None),
        # +1: the warmup (compile) epoch consumes schedule steps too, so
        # the cosine horizon must cover warmup + timed epochs or the last
        # timed epoch trains at lr ~= 0
        train=TrainConfig(num_epochs=epochs + 1,
                          days_per_step=days_per_step,
                          seed=0, checkpoint_every=0,
                          save_dir=f"/tmp/scale_{name}"),
    )
    panel = synthetic_panel_dense(
        num_days=days, num_instruments=s["stocks"],
        num_features=s["num_features"])
    ds = PanelDataset(panel, seq_len=s["seq_len"],
                      max_stocks=s["max_stocks"],
                      pad_multiple=8 * max(1, mesh_stock))
    mesh = make_mesh(MeshConfig(stock_axis=mesh_stock)) \
        if mesh_stock > 1 else None
    trainer = Trainer(cfg, ds, mesh=mesh, logger=MetricsLogger(echo=False))
    state = trainer.init_state()

    # warmup epoch = compile
    t0 = time.time()
    state, m = trainer._train_epoch(state, trainer._epoch_orders(0))
    jax.block_until_ready(m["loss"])
    compile_s = time.time() - t0

    days_per_epoch = float(m["days"])
    t0 = time.time()
    for e in range(1, epochs + 1):
        state, m = trainer._train_epoch(state, trainer._epoch_orders(e))
    jax.block_until_ready(m["loss"])
    dt = time.time() - t0

    dev = jax.devices()[0]
    stats = getattr(dev, "memory_stats", lambda: None)() or {}
    days_per_sec = epochs * days_per_epoch / dt
    return {
        "config": name,
        "platform": dev.platform,
        "mesh_stock": mesh_stock,
        "n_padded": int(ds.n_max),
        "num_features": s["num_features"],
        "seq_len": s["seq_len"],
        "bf16": bf16,
        "days_per_step": days_per_step,
        "compile_seconds": round(compile_s, 1),
        "days_per_sec": round(days_per_sec, 2),
        "windows_per_sec": round(days_per_sec * s["stocks"], 1),
        "loss": float(m["loss"]),
        "hbm_peak_bytes": stats.get("peak_bytes_in_use"),
        "hbm_peak_gb": round(stats.get("peak_bytes_in_use", 0) / 2**30, 3)
                       if stats.get("peak_bytes_in_use") else None,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", default="both",
                    choices=["csi800", "alpha360", "both"])
    ap.add_argument("--days", type=int, default=64)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--days_per_step", type=int, default=8)
    ap.add_argument("--mesh_stock", type=int, default=1,
                    help="size of the 'stock' mesh axis (>1 needs >=2 "
                         "devices; on this sandbox use the virtual CPU "
                         "mesh env — see module docstring)")
    ap.add_argument("--fp32", action="store_true")
    ap.add_argument("--out", default="SCALE_DEMO.json")
    args = ap.parse_args(argv)

    from factorvae_tpu.utils.testing import enable_persistent_compile_cache

    enable_persistent_compile_cache()
    names = ["csi800", "alpha360"] if args.config == "both" else [args.config]
    results = []
    for name in names:
        rec = run_config(name, args.days, args.epochs, args.days_per_step,
                         bf16=not args.fp32, mesh_stock=args.mesh_stock)
        results.append(rec)
        print(json.dumps(rec))
    with open(args.out, "w") as fh:
        json.dump(results, fh, indent=2)
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
