"""Why does K=60 recover only ~53% of reference Rank-IC when a linear
probe recovers 84%? (VERDICT r4 missing-#3 / next-#2.)

The suspect named by the loss structure: the reference's KL term is a
*sum* over K factors while the reconstruction MSE is a *mean* over ~300
stocks (module.py:261,268) — so KL pressure on the posterior/prior pair
scales linearly with K (3x from K=20 to K=60) against a fixed-scale
recon gradient. If that pressure is what caps k60 recovery, the
signature is measurable:

- the per-epoch kl/recon magnitude ratio grows ~3x from the k20 preset
  to the k60 preset at kl_weight=1;
- at K=60 the posterior collapses toward the prior (per-factor
  KL_k -> 0, sigma_post -> sigma_prior) so factors carry little
  day-specific information; and
- down-weighting the KL (kl_weight < 1) should re-open the posterior
  and lift Rank-IC toward the measured 84% linear-probe ceiling
  (SNR_CEILING_r04.json).

This driver measures all three on the same proxy panel as the k60
sweep (scripts/parity_k60_sweep.py): it trains instrumented runs (the
trainer's epoch records now carry train/val recon+kl), then probes the
best-val checkpoint over the validation tail for per-factor posterior
statistics, and scores the reference window for Rank-IC.

Output: K60_DIAGNOSIS.json — per-config loss curves, per-factor KL
spectra, active-factor counts, and recovery fractions; the committed
analysis of those numbers (posterior collapse from epoch ~2, KL ≈ 0,
zero active factors at every preset) lives in the round-5 VERDICT.md
"honest read" entries.

Usage:
    python scripts/k60_diagnose.py [--epochs 18] [--out K60_DIAGNOSIS.json]
        [--runs csi300-k60:1.0,csi300-k60:0.02,csi300-k20:1.0]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import shutil
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from parity_protocol import (  # noqa: E402
    build_proxy_panel,
    load_ref_scores,
    panel_labels,
)

DEFAULT_RUNS = "csi300-k60:1.0,csi300-k60:0.02,csi300-k20:1.0"
ACTIVE_KL_THRESHOLD = 0.01      # nats/factor/day above which a factor is
                                # "carrying day-specific information"


def _cfg_for(preset_name, prefix_dates, window_dates, epochs, kl_weight,
             tag, lr=1e-4):
    from factorvae_tpu.config import Config
    from factorvae_tpu.presets import get_preset

    cfg0 = get_preset(preset_name)
    fit_end = prefix_dates[-61]
    return Config(
        # float32 for statistics runs, as in the sweep driver
        model=dataclasses.replace(cfg0.model, kl_weight=float(kl_weight),
                                  compute_dtype="float32"),
        data=dataclasses.replace(
            cfg0.data,
            dataset_path=None,
            start_time=str(prefix_dates[0].date()),
            fit_end_time=str(fit_end.date()),
            val_start_time=str(prefix_dates[-60].date()),
            val_end_time=str(prefix_dates[-1].date()),
            end_time=str(window_dates[-1].date()),
        ),
        train=dataclasses.replace(
            cfg0.train, num_epochs=int(epochs), lr=float(lr),
            checkpoint_every=0,
            save_dir=os.path.join("/tmp/k60_diag", tag)),
        mesh=cfg0.mesh,
    )


def probe_factors(params, cfg, ds, days, chunk=16):
    """Per-factor posterior statistics over `days`.

    Runs the eval-mode forward (posterior path needs the labels, which
    these validation days have) and returns per-factor, day-averaged:
    KL_k, |mu_post|, sigma_post, sigma_prior, |mu_prior|.
    """
    import jax
    import jax.numpy as jnp

    from factorvae_tpu.data.windows import gather_day
    from factorvae_tpu.models.factorvae import day_forward
    from factorvae_tpu.ops.kl import gaussian_kl

    model = day_forward(cfg.model, train=False)
    seq_len = cfg.data.seq_len

    @jax.jit
    # graftlint: disable=JGL003 diagnostic probe: built once per process for one checkpoint/shape; a config-keyed cache would outlive the single probe call
    def run(params, days, values, last_valid, next_valid, key):
        safe = jnp.maximum(days, 0)
        x, y, mask = jax.vmap(
            lambda d: gather_day(values, last_valid, next_valid, d, seq_len)
        )(safe)
        mask = mask & (days >= 0)[:, None]
        y = jnp.where(mask & jnp.isfinite(y), y, 0.0)
        k1, k2 = jax.random.split(key)
        out = model.apply(params, x, y, mask,
                          rngs={"sample": k1, "dropout": k2})
        guard = jnp.where(out.pred_sigma == 0.0, 1e-6, out.pred_sigma)
        klk = gaussian_kl(out.factor_mu, out.factor_sigma,
                          out.pred_mu, guard)       # (B, K)
        w = (days >= 0).astype(jnp.float32)[:, None]
        return {k: jnp.sum(v * w, axis=0) for k, v in {
            "kl_k": klk,
            "abs_mu_post": jnp.abs(out.factor_mu),
            "sigma_post": out.factor_sigma,
            "abs_mu_prior": jnp.abs(out.pred_mu),
            "sigma_prior": out.pred_sigma,
        }.items()} | {"days": jnp.sum(w)}

    key = jax.random.PRNGKey(0)
    totals = None
    days = np.asarray(days, np.int32)
    pad = (-len(days)) % chunk
    days = np.concatenate([days, np.full(pad, -1, np.int32)])
    for i in range(0, len(days), chunk):
        key, sub = jax.random.split(key)
        part = run(params, jnp.asarray(days[i:i + chunk]),
                   ds.values, ds.last_valid, ds.next_valid, sub)
        part = {k: np.asarray(v) for k, v in part.items()}
        totals = part if totals is None else {
            k: totals[k] + part[k] for k in part}
    n = max(float(totals.pop("days")), 1.0)
    return {k: (v / n) for k, v in totals.items()}


def run_config(preset_name, kl_weight, epochs, panel, prefix_dates,
               window_dates, ref_scores, labels, lr=1e-4):
    from factorvae_tpu.data.loader import PanelDataset
    from factorvae_tpu.eval.compare import compare_scores
    from factorvae_tpu.eval.predict import generate_prediction_scores
    from factorvae_tpu.train.checkpoint import load_params
    from factorvae_tpu.train.trainer import Trainer
    from factorvae_tpu.utils.logging import MetricsLogger

    tag = f"{preset_name}_kl{kl_weight:g}"
    cfg = _cfg_for(preset_name, prefix_dates, window_dates, epochs,
                   kl_weight, tag, lr=lr)
    ds = PanelDataset(panel, seq_len=cfg.model.seq_len, pad_multiple=8)
    shutil.rmtree(cfg.train.save_dir, ignore_errors=True)

    t0 = time.time()
    trainer = Trainer(cfg, ds, logger=MetricsLogger(echo=False))
    state, out = trainer.fit()
    train_s = time.time() - t0

    best = os.path.join(cfg.train.save_dir, cfg.checkpoint_name())
    params = load_params(best, state.params) if os.path.isdir(best) \
        else state.params

    # per-factor posterior statistics on the validation tail
    val_days = ds.split_days(cfg.data.val_start_time,
                             cfg.data.val_end_time)
    stats = probe_factors(params, cfg, ds, val_days)
    kl_k = stats["kl_k"]

    # Rank-IC on the reference score window (deterministic scores)
    scores = generate_prediction_scores(
        params, cfg, ds, start=str(window_dates[0].date()),
        end=str(window_dates[-1].date()), stochastic=False,
        with_labels=True)
    cmp = compare_scores(ref_scores, scores[["score"]], labels,
                         tolerance=0.002)

    hist = out["history"]
    curves = {k: [h[k] for h in hist]
              for k in ("train_loss", "train_recon", "train_kl",
                        "val_loss", "val_recon", "val_kl")}
    # the ratio that scales with K: KL contribution vs recon, in the
    # *reference-faithful* loss (before kl_weight scaling)
    ratio = [k / max(r, 1e-12)
             for k, r in zip(curves["train_kl"], curves["train_recon"])]
    return {
        "preset": preset_name,
        "num_factors": cfg.model.num_factors,
        "kl_weight": kl_weight,
        "lr": lr,
        "epochs": epochs,
        "train_seconds": round(train_s, 1),
        "best_val": float(out["best_val"]),
        "curves": curves,
        "kl_to_recon_ratio": ratio,
        "rank_ic": cmp["ours_rank_ic"],
        "reference_rank_ic": cmp["reference_rank_ic"],
        "recovery_fraction": (cmp["ours_rank_ic"]
                              / cmp["reference_rank_ic"]
                              if cmp["reference_rank_ic"] else None),
        "factor_stats": {
            "per_factor_kl_sorted": sorted(map(float, kl_k), reverse=True),
            "active_factors": int((kl_k > ACTIVE_KL_THRESHOLD).sum()),
            "kl_threshold": ACTIVE_KL_THRESHOLD,
            "total_kl": float(kl_k.sum()),
            "mean_abs_mu_post": float(stats["abs_mu_post"].mean()),
            "mean_sigma_post": float(stats["sigma_post"].mean()),
            "mean_abs_mu_prior": float(stats["abs_mu_prior"].mean()),
            "mean_sigma_prior": float(stats["sigma_prior"].mean()),
        },
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--scores_dir", default="/root/reference/scores")
    ap.add_argument("--epochs", type=int, default=18,
                    help="matches the r4/r5 CPU sweep protocol so runs "
                         "are comparable with PARITY_RUN seeds")
    ap.add_argument("--runs", default=DEFAULT_RUNS,
                    help="comma-separated preset:kl_weight runs")
    ap.add_argument("--out", default="K60_DIAGNOSIS.json")
    ap.add_argument("--quick", action="store_true",
                    help="2 epochs, first run only (smoke)")
    args = ap.parse_args(argv)

    import jax

    from factorvae_tpu.utils.testing import enable_persistent_compile_cache

    enable_persistent_compile_cache()
    ref = load_ref_scores(args.scores_dir)
    panel, prefix_dates, window_dates = build_proxy_panel(ref)
    labels = panel_labels(panel)

    runs = []
    for tok in args.runs.split(","):
        preset, klw = tok.rsplit(":", 1)
        runs.append((preset.strip(), float(klw)))
    epochs = 2 if args.quick else args.epochs
    if args.quick:
        runs = runs[:1]

    results = {
        "question": "why is k60 recovery (53% r2) below the measured "
                    "84% linear-probe ceiling (SNR_CEILING_r04.json)?",
        "protocol": "proxy panel (parity_protocol.build_proxy_panel), "
                    "float32, lr 1e-4, best-val checkpoint selection",
        "platform": jax.devices()[0].platform,
        "epochs": epochs,
        "active_kl_threshold": ACTIVE_KL_THRESHOLD,
        "complete": False,
        "runs": [],
    }

    def flush():
        with open(args.out, "w") as f:
            json.dump(results, f, indent=1)

    flush()
    for preset, klw in runs:
        print(f"[diag] {preset} kl_weight={klw:g} ({epochs} epochs)")
        rec = run_config(preset, klw, epochs, panel, prefix_dates,
                         window_dates, ref[preset], labels)
        results["runs"].append(rec)
        flush()
        fs = rec["factor_stats"]
        recov = (f"{rec['recovery_fraction']:.1%}"
                 if rec["recovery_fraction"] is not None else "n/a")
        print(f"[diag]   ic={rec['rank_ic']:.4f} "
              f"(recovery {recov}) "
              f"active_factors={fs['active_factors']}/"
              f"{rec['num_factors']} total_kl={fs['total_kl']:.3f} "
              f"kl/recon@end={rec['kl_to_recon_ratio'][-1]:.2f} "
              f"({rec['train_seconds']:.0f}s)")

    results["complete"] = True
    flush()
    print(f"[diag] wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
