"""Same-host head-to-head: reference PyTorch implementation vs factorvae_tpu.

Imports the reference code from its read-only mount (running it as a
baseline; nothing is copied) and times per-day training steps of both
frameworks on identical synthetic data and flagship shapes, on this
host's CPU. This pins a *measured* architectural speedup (batched einsum
heads + whole-epoch scan vs K sequential module calls + per-step host
sync) independent of accelerator hardware; the TPU bench (bench.py) then
adds the hardware factor.

Usage: python scripts/bench_reference_cpu.py [--days 8] [--stocks 300] ...
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REFERENCE = os.environ.get("REFERENCE_PATH", "/root/reference")


def bench_reference(args, x, y):
    """Per-day-step seconds for the reference torch implementation."""
    sys.path.insert(0, REFERENCE)
    import torch
    from module import (
        AlphaLayer,
        BetaLayer,
        FactorDecoder,
        FactorEncoder,
        FactorPredictor,
        FactorVAE,
        FeatureExtractor,
    )

    torch.manual_seed(0)
    fe = FeatureExtractor(num_latent=args.features, hidden_size=args.hidden)
    enc = FactorEncoder(num_factors=args.factors, num_portfolio=args.portfolios,
                        hidden_size=args.hidden)
    dec = FactorDecoder(AlphaLayer(args.hidden),
                        BetaLayer(args.hidden, args.factors))
    pred = FactorPredictor(args.hidden, args.factors)
    model = FactorVAE(fe, enc, dec, pred)
    opt = torch.optim.Adam(model.parameters(), lr=1e-4)

    xs = [torch.from_numpy(x[d]) for d in range(args.days)]
    ys = [torch.from_numpy(y[d]).reshape(-1, 1) for d in range(args.days)]

    def step(d):
        opt.zero_grad()
        loss, *_ = model(xs[d], ys[d])
        loss.backward()
        opt.step()

    for d in range(min(2, args.days)):  # warmup
        step(d)
    t0 = time.time()
    for _ in range(args.reps):
        for d in range(args.days):
            step(d)
    dt = time.time() - t0
    return dt / (args.reps * args.days)


def bench_ours(args, x, y):
    """Per-day-step seconds for factorvae_tpu on the JAX CPU backend."""
    sys.path.insert(0, REPO)
    from factorvae_tpu.utils.testing import force_host_devices

    force_host_devices(1)

    import numpy as np

    from factorvae_tpu.config import Config, DataConfig, ModelConfig, TrainConfig
    from factorvae_tpu.data import PanelDataset
    from factorvae_tpu.data.panel import Panel
    from factorvae_tpu.train import Trainer
    from factorvae_tpu.utils.logging import MetricsLogger

    import pandas as pd

    feats = np.swapaxes(x[:, :, -1, :], 0, 1)  # (N, D, C): last window row
    labels = np.swapaxes(y, 0, 1)[..., None]   # (N, D, 1)
    values = np.concatenate([feats, labels], axis=-1)
    panel = Panel(
        values=values.astype(np.float32),
        valid=np.ones((args.days, args.stocks), bool),
        dates=pd.bdate_range("2020-01-01", periods=args.days),
        instruments=np.array([f"I{i}" for i in range(args.stocks)]),
    )
    ds = PanelDataset(panel, seq_len=args.seq_len, pad_multiple=4)
    cfg = Config(
        model=ModelConfig(num_features=args.features, hidden_size=args.hidden,
                          num_factors=args.factors,
                          num_portfolios=args.portfolios, seq_len=args.seq_len),
        data=DataConfig(seq_len=args.seq_len, start_time=None, fit_end_time=None,
                        val_start_time=None, val_end_time=None),
        train=TrainConfig(num_epochs=1 + args.reps,
                          days_per_step=args.ours_days_per_step, seed=0,
                          checkpoint_every=0, save_dir="/tmp/factorvae_cmp"),
    )
    trainer = Trainer(cfg, ds, logger=MetricsLogger(echo=False))
    state = trainer.init_state()
    import jax

    order = trainer._epoch_orders(0)
    state, m = trainer._train_epoch(state, order)  # warmup/compile
    jax.block_until_ready(m["loss"])
    t0 = time.time()
    for e in range(1, 1 + args.reps):
        state, m = trainer._train_epoch(state, trainer._epoch_orders(e))
    jax.block_until_ready(m["loss"])
    dt = time.time() - t0
    return dt / (args.reps * args.days)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--days", type=int, default=8)
    p.add_argument("--stocks", type=int, default=300)
    p.add_argument("--features", type=int, default=158)
    p.add_argument("--seq_len", type=int, default=20)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--factors", type=int, default=96)
    p.add_argument("--portfolios", type=int, default=128)
    p.add_argument("--reps", type=int, default=3)
    p.add_argument("--ours_days_per_step", type=int, default=1,
                   help="batched-update mode for the jax side (1 = faithful)")
    p.add_argument("--skip", choices=["none", "reference", "ours"], default="none")
    args = p.parse_args()

    import numpy as np

    rng = np.random.default_rng(0)
    # windows for torch path: (D, N, T, C); flat panel features for ours
    x = rng.normal(size=(args.days, args.stocks, args.seq_len, args.features)
                   ).astype(np.float32)
    y = rng.normal(size=(args.days, args.stocks)).astype(np.float32) * 0.02

    out = {"shapes": vars(args)}
    if args.skip != "reference":
        out["reference_torch_cpu_sec_per_day_step"] = bench_reference(args, x, y)
    if args.skip != "ours":
        out["factorvae_tpu_jax_cpu_sec_per_day_step"] = bench_ours(args, x, y)
    if args.skip == "none":
        out["speedup_same_host_cpu"] = (
            out["reference_torch_cpu_sec_per_day_step"]
            / out["factorvae_tpu_jax_cpu_sec_per_day_step"]
        )
    print(json.dumps(out))


if __name__ == "__main__":
    main()
