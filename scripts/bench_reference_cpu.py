"""Same-host head-to-head: reference PyTorch implementation vs factorvae_tpu.

Imports the reference code from its read-only mount (running it as a
baseline; nothing is copied) and times BOTH frameworks on identical
synthetic data, on this host's CPU, in the same process environment.
This pins a *measured* architectural speedup (batched einsum heads +
whole-epoch scan + cross-day flattening vs K sequential module calls +
per-step host sync) independent of accelerator hardware; the TPU bench
(bench.py) then adds the hardware factor.

Measured per config (VERDICT r4 next-#5):
- train: per-day training-step seconds, reference vs ours at
  days_per_step=1 (reference-faithful) and ours at days_per_step=8
  (the flattened default operating point; flatten_days=True);
- scoring: prediction windows/second over a D-day panel, reference
  (per-day `model.prediction` under no_grad, utils.py:70-87) vs ours
  (the single-dispatch jitted-scan `predict_panel`, run with the
  execution planner's scoring knobs for this backend+shape);
- plan: what the execution planner (factorvae_tpu/plan.py) chooses for
  this backend+shape, with provenance ("measured" envelope row vs the
  conservative per-backend "default"), plus a timed run of the planner's
  TRAIN choice (`ours_train_sec_per_day_plan`) — the check that a
  planner decision is never slower than the best measured path.

Configs mirror the BASELINE.json preset shapes (presets.py): flagship
(H=64/K=96), csi300-k60 (H=K=60), csi800-k60 (N=800) and alpha360-k60
(C=360, T=60).

Usage:
    python scripts/bench_reference_cpu.py                # one config
    python scripts/bench_reference_cpu.py --table        # all 4 + markdown
    python scripts/bench_reference_cpu.py --config csi800-k60 --reps 2

`--table` always writes the machine-readable artifact (default
BENCH_REFERENCE_CPU.json, override with --out): rows + markdown + every
`plan` block, so future rounds can diff planner decisions.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REFERENCE = os.environ.get("REFERENCE_PATH", "/root/reference")

# Preset-shaped benchmark configs (shapes per factorvae_tpu/presets.py;
# stock counts per BASELINE.md: CSI300 ~300 names, CSI800 ~800).
CONFIGS = {
    "flagship": dict(stocks=300, features=158, seq_len=20, hidden=64,
                     factors=96, portfolios=128),
    "csi300-k60": dict(stocks=300, features=158, seq_len=20, hidden=60,
                       factors=60, portfolios=128),
    # N=800: the real CSI800 cross-section. Through r05 this row ran at
    # 1024 (the old fixed max_stocks pad); the scale-aware pad policy
    # now pads 800 -> 800, so 1024 no longer corresponds to any
    # production configuration — absolute csi800 rows are therefore not
    # comparable with pre-PR-1 tables (the r05-path scoring A/B column
    # is, it runs both paths at the same width).
    "csi800-k60": dict(stocks=800, features=158, seq_len=20, hidden=60,
                       factors=60, portfolios=128),
    "alpha360-k60": dict(stocks=300, features=360, seq_len=60, hidden=60,
                         factors=60, portfolios=128),
}


def _ref_model(args):
    sys.path.insert(0, REFERENCE)
    import torch
    from module import (
        AlphaLayer,
        BetaLayer,
        FactorDecoder,
        FactorEncoder,
        FactorPredictor,
        FactorVAE,
        FeatureExtractor,
    )

    torch.manual_seed(0)
    fe = FeatureExtractor(num_latent=args.features, hidden_size=args.hidden)
    enc = FactorEncoder(num_factors=args.factors, num_portfolio=args.portfolios,
                        hidden_size=args.hidden)
    dec = FactorDecoder(AlphaLayer(args.hidden),
                        BetaLayer(args.hidden, args.factors))
    pred = FactorPredictor(args.hidden, args.factors)
    return FactorVAE(fe, enc, dec, pred)


def bench_reference(args, x, y):
    """Per-day-step seconds for the reference torch implementation."""
    import torch

    model = _ref_model(args)
    opt = torch.optim.Adam(model.parameters(), lr=1e-4)

    xs = [torch.from_numpy(x[d]) for d in range(args.days)]
    ys = [torch.from_numpy(y[d]).reshape(-1, 1) for d in range(args.days)]

    def step(d):
        opt.zero_grad()
        loss, *_ = model(xs[d], ys[d])
        loss.backward()
        opt.step()

    for d in range(min(2, args.days)):  # warmup
        step(d)
    t0 = time.time()
    for _ in range(args.reps):
        for d in range(args.days):
            step(d)
    dt = time.time() - t0
    return dt / (args.reps * args.days)


def bench_reference_scoring(args, x):
    """Prediction windows/sec for the reference (the utils.py:70-87
    scoring loop: per-day `model.prediction` under no_grad)."""
    import torch

    model = _ref_model(args)
    model.eval()
    xs = [torch.from_numpy(x[d]) for d in range(args.days)]
    with torch.no_grad():
        for d in range(min(2, args.days)):  # warmup
            model.prediction(xs[d])
        t0 = time.time()
        for _ in range(args.reps):
            for d in range(args.days):
                model.prediction(xs[d])
        dt = time.time() - t0
    n_windows = args.reps * args.days * args.stocks
    return dt / (args.reps * args.days), n_windows / dt


def _ours_setup(args, x, y):
    """Panel built from the SAME arrays the torch path consumes: panel
    features at day d are x[d, :, -1, :] (the window's last row), so
    both sides train on identical synthetic data; the window gather
    reconstructs per-day windows from the panel on device."""
    sys.path.insert(0, REPO)
    from factorvae_tpu.utils.testing import force_host_devices

    force_host_devices(1)

    import numpy as np
    import pandas as pd

    from factorvae_tpu.config import Config, DataConfig, ModelConfig, TrainConfig
    from factorvae_tpu.data import PanelDataset
    from factorvae_tpu.data.panel import Panel

    feats = np.swapaxes(x[:, :, -1, :], 0, 1)  # (N, D, C): last window row
    labels = np.swapaxes(y, 0, 1)[..., None]   # (N, D, 1)
    values = np.concatenate([feats, labels], axis=-1).astype(np.float32)
    panel = Panel(
        values=values,
        valid=np.ones((args.days, args.stocks), bool),
        dates=pd.bdate_range("2020-01-01", periods=args.days),
        instruments=np.array([f"I{i}" for i in range(args.stocks)]),
    )
    ds = PanelDataset(panel, seq_len=args.seq_len, pad_multiple=4)
    cfg = Config(
        model=ModelConfig(num_features=args.features, hidden_size=args.hidden,
                          num_factors=args.factors,
                          num_portfolios=args.portfolios, seq_len=args.seq_len,
                          compute_dtype=getattr(args, "ours_dtype",
                                                "bfloat16"),
                          flatten_days=getattr(args, "ours_flatten", True)),
        data=DataConfig(seq_len=args.seq_len, start_time=None, fit_end_time=None,
                        val_start_time=None, val_end_time=None),
        train=TrainConfig(num_epochs=1 + args.reps,
                          days_per_step=args.ours_days_per_step, seed=0,
                          checkpoint_every=0, save_dir="/tmp/factorvae_cmp"),
    )
    return cfg, ds


def bench_ours(args, x, y):
    """Per-day-step seconds for factorvae_tpu on the JAX CPU backend."""
    cfg, ds = _ours_setup(args, x, y)
    import jax

    from factorvae_tpu.train import Trainer
    from factorvae_tpu.utils.logging import MetricsLogger

    trainer = Trainer(cfg, ds, logger=MetricsLogger(echo=False))
    state = trainer.init_state()
    order = trainer._epoch_orders(0)
    state, m = trainer._train_epoch(state, order)  # warmup/compile
    jax.block_until_ready(m["loss"])
    t0 = time.time()
    for e in range(1, 1 + args.reps):
        state, m = trainer._train_epoch(state, trainer._epoch_orders(e))
    jax.block_until_ready(m["loss"])
    dt = time.time() - t0
    return dt / (args.reps * args.days)


def bench_ours_scoring(args, x, y):
    """Prediction windows/sec for ours (the jitted `predict_panel`
    scoring path; `args.ours_impl` picks "scan" — the single-dispatch
    overhaul default — or "chunk_loop", the pre-overhaul per-chunk
    dispatch loop kept for exactly this A/B). NOTE: includes the
    on-device window gather the torch loop gets for free (its loader
    cost is excluded); see PERF.md round-5 caveats."""
    from factorvae_tpu.eval.predict import predict_panel
    from factorvae_tpu.train import Trainer
    from factorvae_tpu.utils.logging import MetricsLogger

    impl = getattr(args, "ours_impl", "scan")
    cfg, ds = _ours_setup(args, x, y)
    trainer = Trainer(cfg, ds, logger=MetricsLogger(echo=False))
    state = trainer.init_state()
    days = ds.split_days(None, None)
    chunk = min(16, len(days))
    # predict_panel returns a host numpy array — already synchronized
    predict_panel(state.params, cfg, ds, days, stochastic=False,
                  chunk=chunk, impl=impl)  # warmup/compile
    t0 = time.time()
    for _ in range(args.reps):
        predict_panel(state.params, cfg, ds, days, stochastic=False,
                      chunk=chunk, impl=impl)
    dt = time.time() - t0
    n_windows = args.reps * args.days * args.stocks
    return dt / (args.reps * args.days), n_windows / dt


def _plan_for_shapes(shapes: dict):
    """Execution-planner decision for one benchmark config on this host."""
    sys.path.insert(0, REPO)
    from factorvae_tpu import plan as planlib

    shape = planlib.ShapeKey(
        num_features=shapes["features"], seq_len=shapes["seq_len"],
        hidden_size=shapes["hidden"], num_factors=shapes["factors"],
        num_portfolios=shapes["portfolios"], n_stocks=shapes["stocks"])
    pl = planlib.plan_for(shape, platform="cpu")
    return pl, pl.describe(shape, platform="cpu")


def plan_label(plan_block: dict) -> str:
    """Compact one-cell rendering of a plan block for the markdown table."""
    d = plan_block
    dt = {"float32": "f32", "bfloat16": "bf16"}.get(
        d["compute_dtype"], d["compute_dtype"])
    sdt = {"float32": "f32", "bfloat16": "bf16"}.get(
        d["score_compute_dtype"], d["score_compute_dtype"])
    return (f"dps{d['days_per_step']}·{dt}"
            f"·{'flat' if d['flatten_days'] else 'unflat'}"
            f" / score {sdt}"
            f"·{'flat' if d['score_flatten_days'] else 'unflat'}"
            f" [{d['provenance']}]")


def run_config(name: str, shapes: dict, reps: int, skip: str,
               ours_dtype: str = "bfloat16", days: int = 8) -> dict:
    """One head-to-head row: train (dps=1 + dps=8 flattened + the
    planner's choice) + scoring (planner knobs)."""
    import numpy as np

    ns = argparse.Namespace(days=days, reps=reps, ours_days_per_step=1,
                            ours_dtype=ours_dtype, **shapes)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(ns.days, ns.stocks, ns.seq_len, ns.features)
                   ).astype(np.float32)
    y = (rng.normal(size=(ns.days, ns.stocks)) * 0.02).astype(np.float32)

    pl, plan_block = _plan_for_shapes(shapes)
    row = {"config": name, "shapes": shapes, "days": ns.days, "reps": reps,
           "ours_dtype": ours_dtype, "plan": plan_block}
    if skip != "reference":
        row["ref_train_sec_per_day"] = bench_reference(ns, x, y)
        row["ref_score_sec_per_day"], row["ref_score_windows_per_sec"] = \
            bench_reference_scoring(ns, x)
    if skip != "ours":
        row["ours_train_sec_per_day_dps1"] = bench_ours(ns, x, y)
        ns8 = argparse.Namespace(**{**vars(ns), "ours_days_per_step": 8})
        row["ours_train_sec_per_day_dps8_flat"] = bench_ours(ns8, x, y)
        # The planner's training choice, timed as its own row — with a
        # "measured" row this must match that row's raced winner; with
        # the "default" fallback it is the conservative per-backend path.
        nsp = argparse.Namespace(**{
            **vars(ns), "ours_days_per_step": pl.days_per_step,
            "ours_dtype": pl.compute_dtype, "ours_flatten": pl.flatten_days})
        row["ours_train_sec_per_day_plan"] = bench_ours(nsp, x, y)
        # Scoring runs the planner's scoring knobs (the production
        # default: cli --auto_plan / eval wiring score with these).
        nss = argparse.Namespace(**{
            **vars(ns), "ours_dtype": pl.score_compute_dtype,
            "ours_flatten": pl.score_flatten_days})
        row["ours_score_sec_per_day"], row["ours_score_windows_per_sec"] = \
            bench_ours_scoring(nss, x, y)
        # The r05-era scoring path (bf16, flattened, per-chunk Python
        # dispatch loop) timed on THIS host, so the artifact carries the
        # overhaul's own A/B even when the torch reference mount is
        # absent: new-vs-old ratio on identical hardware.
        nsr = argparse.Namespace(**{
            **vars(ns), "ours_dtype": "bfloat16", "ours_flatten": True,
            "ours_impl": "chunk_loop"})
        _, row["ours_score_windows_per_sec_r05_path"] = \
            bench_ours_scoring(nsr, x, y)
        row["score_path_speedup_vs_r05"] = (
            row["ours_score_windows_per_sec"]
            / row["ours_score_windows_per_sec_r05_path"])
    if skip == "none":
        row["train_speedup_dps1"] = (row["ref_train_sec_per_day"]
                                     / row["ours_train_sec_per_day_dps1"])
        row["train_speedup_dps8_flat"] = (
            row["ref_train_sec_per_day"]
            / row["ours_train_sec_per_day_dps8_flat"])
        row["train_speedup_plan"] = (row["ref_train_sec_per_day"]
                                     / row["ours_train_sec_per_day_plan"])
        row["score_speedup"] = (row["ref_score_sec_per_day"]
                                / row["ours_score_sec_per_day"])
    return row


def markdown_table(rows) -> str:
    hdr = ("| config | ref train s/day | ours s/day (dps=1) | ours s/day "
           "(dps=8 flat) | ours s/day (plan) | train × (plan) | ref score "
           "w/s | ours score w/s | score × | plan |")
    sep = "|" + "---|" * 10
    lines = [hdr, sep]

    def fmt(r, key, spec, suffix=""):
        # --skip reference/ours rows lack the other side's columns
        return format(r[key], spec) + suffix if key in r else "—"

    for r in rows:
        lines.append(
            "| {config} | {ref} | {o1} | {o8} | {op} | {sp} | {rw} | "
            "{ow} | {ss} | {plan} |".format(
                config=r["config"],
                ref=fmt(r, "ref_train_sec_per_day", ".3f"),
                o1=fmt(r, "ours_train_sec_per_day_dps1", ".3f"),
                o8=fmt(r, "ours_train_sec_per_day_dps8_flat", ".3f"),
                op=fmt(r, "ours_train_sec_per_day_plan", ".3f"),
                sp=fmt(r, "train_speedup_plan", ".2f", "×"),
                rw=fmt(r, "ref_score_windows_per_sec", ",.0f"),
                ow=fmt(r, "ours_score_windows_per_sec", ",.0f"),
                ss=fmt(r, "score_speedup", ".2f", "×"),
                plan=plan_label(r["plan"])))
    return "\n".join(lines)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--days", type=int, default=8)
    p.add_argument("--stocks", type=int, default=300)
    p.add_argument("--features", type=int, default=158)
    p.add_argument("--seq_len", type=int, default=20)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--factors", type=int, default=96)
    p.add_argument("--portfolios", type=int, default=128)
    p.add_argument("--reps", type=int, default=3)
    p.add_argument("--ours_days_per_step", type=int, default=1,
                   help="batched-update mode for the jax side (1 = faithful)")
    p.add_argument("--skip", choices=["none", "reference", "ours"], default="none")
    p.add_argument("--config", choices=sorted(CONFIGS), default=None,
                   help="use a preset-shaped config instead of the flags")
    p.add_argument("--table", action="store_true",
                   help="run ALL preset configs (train dps=1/dps=8 + "
                        "scoring) and print the PERF.md markdown table")
    p.add_argument("--ours_dtype", default="bfloat16",
                   choices=["bfloat16", "float32"],
                   help="compute dtype for the jax side. bfloat16 is the "
                        "shipped TPU default but is partly EMULATED on "
                        "CPU; float32 is the apples-to-apples dtype vs "
                        "torch's fp32 MKL path")
    p.add_argument("--out", default=None,
                   help="write the JSON artifact here (--table default: "
                        "BENCH_REFERENCE_CPU.json at the repo root)")
    args = p.parse_args()

    import numpy as np

    # Skip the torch side cleanly when the reference mount is absent
    # (same idiom as scripts/qlib_differential.py): the ours-side
    # columns, the plan column and the r05-path scoring A/B still run,
    # and the artifact records why the ref columns are missing.
    reference_available = os.path.exists(os.path.join(REFERENCE, "module.py"))
    if not reference_available and args.skip != "ours":
        print(f"[h2h] reference mount not found at {REFERENCE} "
              f"(set REFERENCE_PATH); skipping the torch side",
              file=sys.stderr)
        args.skip = "reference"

    if args.table:
        rows = []
        for name, shapes in CONFIGS.items():
            print(f"[h2h] {name}: {shapes}", file=sys.stderr)
            rows.append(run_config(name, shapes, args.reps, args.skip,
                                   ours_dtype=args.ours_dtype,
                                   days=args.days))
            print(json.dumps(rows[-1]), file=sys.stderr)
        from factorvae_tpu.plan import table_path

        out = {"rows": rows, "markdown": markdown_table(rows),
               # the artifact future rounds diff planner decisions from
               "plans": {r["config"]: r["plan"] for r in rows},
               "plan_table": table_path(),
               "reference_available": reference_available,
               "environment": f"same host, {os.cpu_count()} CPU core(s), "
                              f"torch fp32 vs jax "
                              f"({args.ours_dtype} compute; scoring runs "
                              f"the planner's knobs)"}
        print(json.dumps(out))
        artifact = args.out or os.path.join(REPO, "BENCH_REFERENCE_CPU.json")
        with open(artifact, "w") as f:
            json.dump(out, f, indent=1)
        print(f"[h2h] artifact -> {artifact}", file=sys.stderr)
        print("\n" + out["markdown"], file=sys.stderr)
        return

    if args.config:
        for k, v in CONFIGS[args.config].items():
            setattr(args, k, v)

    rng = np.random.default_rng(0)
    # windows for torch path: (D, N, T, C); flat panel features for ours
    x = rng.normal(size=(args.days, args.stocks, args.seq_len, args.features)
                   ).astype(np.float32)
    y = rng.normal(size=(args.days, args.stocks)).astype(np.float32) * 0.02

    out = {"shapes": vars(args)}
    if args.skip != "reference":
        out["reference_torch_cpu_sec_per_day_step"] = bench_reference(args, x, y)
    if args.skip != "ours":
        out["factorvae_tpu_jax_cpu_sec_per_day_step"] = bench_ours(args, x, y)
    if args.skip == "none":
        out["speedup_same_host_cpu"] = (
            out["reference_torch_cpu_sec_per_day_step"]
            / out["factorvae_tpu_jax_cpu_sec_per_day_step"]
        )
    print(json.dumps(out))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
