"""Race the Pallas kernels against the XLA paths on real TPU hardware.

VERDICT r1 item 3: both kernels must go through Mosaic (not interpret)
at N_max in {360, 1024}, H in {20..64}, K in {20..96}, and be timed
against the XLA einsum/scan paths so the winner per shape is measured,
not assumed. Emits a JSON list (one record per shape) and a markdown
table for PERF.md.

The XLA oracles here are the exact computations models/{layers,
predictor}.py run when use_pallas_* is off: a `lax.scan` GRU recurrence
and the batched K-head einsum attention (both operating on the same
pre-computed inputs the kernels take, so the race isolates the fused
part).

Usage: python scripts/race_kernels.py [--out RACE.json] [--reps 20]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def timed(fn, *args, reps: int = 20) -> float:
    """Median wall seconds of jitted fn over reps (after warmup)."""
    out = fn(*args)
    jax.block_until_ready(out)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def gru_xla(xi, wh, bh):
    """The models/layers.py scan recurrence on precomputed projections."""
    h = wh.shape[0]

    def step(hc, xt):
        gh = hc @ wh + bh
        r = jax.nn.sigmoid(xt[:, :h] + gh[:, :h])
        z = jax.nn.sigmoid(xt[:, h:2 * h] + gh[:, h:2 * h])
        n = jnp.tanh(xt[:, 2 * h:] + r * gh[:, 2 * h:])
        return (1 - z) * n + z * hc, None

    h0 = jnp.zeros((xi.shape[0], h))
    out, _ = jax.lax.scan(step, h0, jnp.transpose(xi, (1, 0, 2)))
    return out


def attn_xla(latent, maskf, q, wk, bk, wv, bv):
    """The models/predictor.py batched K-head einsum path."""
    h = latent.shape[1]
    key = jnp.einsum("nh,khj->knj", latent, wk) + bk[:, None, :]
    val = jnp.einsum("nh,khj->knj", latent, wv) + bv[:, None, :]
    scores = jnp.einsum("knh,kh->kn", key, q) / jnp.sqrt(
        jnp.float32(h) + 1e-6)
    scores = jnp.maximum(scores, 0.0)
    neg = jnp.where(maskf[None, :] > 0, scores, -1e30)
    m = jnp.max(neg, axis=1, keepdims=True)
    ex = jnp.where(maskf[None, :] > 0, jnp.exp(neg - m), 0.0)
    attn = ex / jnp.maximum(jnp.sum(ex, axis=1, keepdims=True), 1e-30)
    return jnp.einsum("kn,knh->kh", attn, jnp.nan_to_num(val))


def race_gru(n, t, h, reps):
    from factorvae_tpu.ops.pallas.gru import gru_scan

    rng = np.random.default_rng(0)
    xi = jnp.asarray(rng.normal(size=(n, t, 3 * h)), jnp.float32) * 0.5
    wh = jnp.asarray(rng.normal(size=(h, 3 * h)), jnp.float32) * 0.2
    bh = jnp.asarray(rng.normal(size=(3 * h,)), jnp.float32) * 0.1

    rec = {"op": "gru", "n": n, "t": t, "h": h}
    for name, f in (("pallas", gru_scan), ("xla", gru_xla)):
        # graftlint: disable=JGL003 racing harness: each candidate is jitted exactly once per process; timed() warms up first so compile never lands in the measurement
        fwd = jax.jit(lambda a, b, c, f=f: f(a, b, c))
        # graftlint: disable=JGL003 same one-compile-per-candidate contract as fwd above
        bwd = jax.jit(jax.grad(
            lambda a, b, c, f=f: jnp.sum(f(a, b, c) ** 2), argnums=(0, 1, 2)))
        rec[f"{name}_fwd_us"] = round(timed(fwd, xi, wh, bh, reps=reps) * 1e6, 1)
        rec[f"{name}_fwdbwd_us"] = round(
            timed(bwd, xi, wh, bh, reps=reps) * 1e6, 1)
    rec["fwd_speedup"] = round(rec["xla_fwd_us"] / rec["pallas_fwd_us"], 2)
    rec["fwdbwd_speedup"] = round(
        rec["xla_fwdbwd_us"] / rec["pallas_fwdbwd_us"], 2)
    return rec


def race_attention(n, h, k, reps):
    from factorvae_tpu.ops.pallas.attention_grad import fused_attention

    rng = np.random.default_rng(0)
    latent = jnp.asarray(rng.normal(size=(n, h)), jnp.float32)
    maskf = jnp.asarray(rng.random(n) < 0.9, jnp.float32)
    q = jnp.asarray(rng.normal(size=(k, h)), jnp.float32)
    wk = jnp.asarray(rng.normal(size=(k, h, h)), jnp.float32) * 0.1
    bk = jnp.zeros((k, h))
    wv = wk * 0.5
    bv = jnp.zeros((k, h))
    args = (latent, maskf, q, wk, bk, wv, bv)

    rec = {"op": "attention", "n": n, "h": h, "k": k}
    for name, f in (("pallas", fused_attention), ("xla", attn_xla)):
        # graftlint: disable=JGL003 racing harness: one compile per candidate per process, warmed up before timing
        fwd = jax.jit(lambda *a, f=f: f(*a))
        # grads w.r.t. ALL trainable inputs (latent, q, Wk, bk, Wv, bv) so
        # both paths time the full training-relevant backward
        # graftlint: disable=JGL003 same one-compile-per-candidate contract as fwd above
        bwd = jax.jit(jax.grad(
            lambda *a, f=f: jnp.sum(f(*a) ** 2),
            argnums=(0, 2, 3, 4, 5, 6)))
        rec[f"{name}_fwd_us"] = round(timed(fwd, *args, reps=reps) * 1e6, 1)
        rec[f"{name}_fwdbwd_us"] = round(
            timed(bwd, *args, reps=reps) * 1e6, 1)
    rec["fwd_speedup"] = round(rec["xla_fwd_us"] / rec["pallas_fwd_us"], 2)
    rec["fwdbwd_speedup"] = round(
        rec["xla_fwdbwd_us"] / rec["pallas_fwdbwd_us"], 2)
    return rec


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="RACE_KERNELS.json")
    ap.add_argument("--reps", type=int, default=20)
    ap.add_argument("--max_n", type=int, default=None,
                    help="skip grid rows with n above this (CPU smoke of "
                         "the driver: --max_n 360 --reps 1)")
    args = ap.parse_args(argv)

    from factorvae_tpu.utils.testing import enable_persistent_compile_cache

    enable_persistent_compile_cache()
    backend = jax.default_backend()
    records = []
    # 2880 = the cross-day-flattened flagship GRU row count
    # (days_per_step=8 x N_pad=360, PERF.md "Round 3"): the kernels' real
    # r3 operating point for the day-independent segment.
    for n in (360, 1024, 2880):
        if args.max_n and n > args.max_n:
            continue
        for t, h in ((20, 20), (20, 64), (60, 64)):
            rec = race_gru(n, t, h, args.reps)
            records.append(rec)
            print(json.dumps(rec))
    for n in (360, 1024):
        if args.max_n and n > args.max_n:
            continue
        for h, k in ((20, 20), (48, 48), (64, 96)):
            rec = race_attention(n, h, k, args.reps)
            records.append(rec)
            print(json.dumps(rec))
    with open(args.out, "w") as fh:
        json.dump({"backend": backend, "records": records}, fh, indent=2)
    print(f"wrote {args.out} (backend={backend})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
