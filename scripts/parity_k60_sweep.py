"""k60 statistical-parity sweep (VERDICT r2 #6).

Round 2's proxy-protocol k60 row recovered only ~53% of the reference
Rank-IC (0.0423±0.0100 over 3 seeds vs 0.0794), with the largest config
exactly where the framework underperformed. This driver tightens that
claim in two phases on the same proxy panel as scripts/parity_protocol.py
(window alpha = the real reference K=60 scores):

1. GRID: a small hyperparameter search over (lr, kl_weight, epochs) —
   the levers VERDICT r2 #6 names. `kl_weight` scales the summed-over-K
   KL term (ModelConfig.kl_weight; 1.0 = reference-faithful loss): at
   K=60 the KL sum is ~3x the K=20 one against the same mean-over-N MSE,
   so the reference's unweighted sum (module.py:268) suppresses the
   reconstruction gradient precisely at large K.
2. SWEEP: >= 8 seeds at the grid winner, reporting mean, std and a 95%
   normal-approximation CI, plus the reference-faithful (kl_weight=1)
   8-seed row for honest comparison.

Output: PARITY_RUN_r04.json (grid table + both sweeps + the recovery
fraction vs the reference's 0.0794). Runs are float32 regardless of the
preset's bench dtype.

The seed sweeps run seed-parallel by default when the planner says the
fleet pays at this shape (`--fleet auto`: `seeds_per_program` from the
raced plan row, train/fleet.py; `--fleet on|off` forces it) — the
epoch-matched 50-epoch control (VERDICT r5 weak-#3: the missing
experiment separating "collapsed" from "undertrained") is affordable
exactly because S seeds share one program. Partial-result files stay
format-compatible either way: `on_seed` fires per seed in both modes.
Restart granularity differs: serial loses at most the in-flight seed,
fleet at most the in-flight GROUP (bounded by the planner's
seeds_per_program — these runs keep checkpoint_every=0 for speed, so
mid-group state is not checkpointed here).

Usage:
    python scripts/parity_k60_sweep.py [--epochs 50] [--seeds 8]
        [--fleet auto|on|off] [--out PARITY_RUN_r04.json] [--quick]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import shutil
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from parity_protocol import (  # noqa: E402
    build_proxy_panel,
    load_ref_scores,
    panel_labels,
)

PRESET = "csi300-k60"


def _cfg_for(cfg0, prefix_dates, window_dates, epochs,
             lr, kl_weight, tag):
    from factorvae_tpu.config import Config

    fit_end = prefix_dates[-61]
    return Config(
        # Statistics-sensitive sweep: force float32 regardless of the
        # preset (presets default to bf16 for bench; parity numbers
        # should not fold a dtype change in).
        model=dataclasses.replace(cfg0.model, kl_weight=float(kl_weight),
                                  compute_dtype="float32"),
        data=dataclasses.replace(
            cfg0.data,
            dataset_path=None,
            start_time=str(prefix_dates[0].date()),
            fit_end_time=str(fit_end.date()),
            val_start_time=str(prefix_dates[-60].date()),
            val_end_time=str(prefix_dates[-1].date()),
            end_time=str(window_dates[-1].date()),
        ),
        train=dataclasses.replace(
            cfg0.train, num_epochs=int(epochs), lr=float(lr),
            checkpoint_every=0,
            save_dir=os.path.join("/tmp/parity_k60", tag)),
        mesh=cfg0.mesh,
    )


def _compare_point(cfg, ds, params, ref_scores, labels,
                   score_start, score_end) -> dict:
    """Score one trained config over the proxy window and compare to
    the reference scores — the protocol half of `_run_one`, shared by
    the serial grid and the hyper-fleet grid so both phases report the
    SAME statistic."""
    from factorvae_tpu.eval.compare import compare_scores
    from factorvae_tpu.eval.predict import generate_prediction_scores

    scores = generate_prediction_scores(
        params, cfg, ds, start=score_start, end=score_end,
        stochastic=False, with_labels=True)
    cmp = compare_scores(ref_scores, scores[["score"]], labels,
                         tolerance=0.002)
    return {
        "rank_ic": cmp["ours_rank_ic"],
        "rank_ic_ir": cmp["ours_rank_ic_ir"],
        "reference_rank_ic": cmp["reference_rank_ic"],
    }


def _run_one(cfg, ds, ref_scores, labels, score_start, score_end,
             logger=None):
    from factorvae_tpu.train.checkpoint import load_params
    from factorvae_tpu.train.trainer import Trainer
    from factorvae_tpu.utils.logging import MetricsLogger

    shutil.rmtree(cfg.train.save_dir, ignore_errors=True)
    t0 = time.time()
    trainer = Trainer(cfg, ds, logger=logger or MetricsLogger(echo=False))
    state, out = trainer.fit()
    best = os.path.join(cfg.train.save_dir, cfg.checkpoint_name())
    params = load_params(best, state.params) if os.path.isdir(best) \
        else state.params
    rec = _compare_point(cfg, ds, params, ref_scores, labels,
                         score_start, score_end)
    rec.update(best_val=float(out["best_val"]),
               train_seconds=round(time.time() - t0, 1))
    return rec


def _run_grid_hyper(cfg0, ds, grid, prefix_dates, window_dates, epochs,
                    ref_scores, labels, score_start, score_end, logger,
                    lanes_per_program=None):
    """The whole (lr x kl_weight) grid phase as hyper-fleet programs
    (ISSUE 12): every pending grid point is one LANE of a stacked
    program — its (lr, kl_weight) ride the vmapped trace as runtime
    scalars (train/fleet.py lane_configs), so the grid pays ONE compile
    instead of one per point. Scoring and the reference comparison run
    per lane through the SAME `_compare_point` protocol as the serial
    grid, and records keep the serial grid's keys (resume files stay
    format-compatible; `hyper_fleet`/`train_seconds` annotate the
    shared program wall)."""
    import jax

    from factorvae_tpu.train.checkpoint import load_params
    from factorvae_tpu.train.fleet import FleetTrainer, unstack_state

    lanes = []
    for lr, klw in grid:
        cfg = _cfg_for(cfg0, prefix_dates, window_dates, epochs, lr, klw,
                       f"lr{lr:g}_kl{klw:g}")
        shutil.rmtree(cfg.train.save_dir, ignore_errors=True)
        lanes.append(cfg)
    base = _cfg_for(cfg0, prefix_dates, window_dates, epochs,
                    grid[0][0], grid[0][1], "hyper_base")
    recs = []
    spp = (len(lanes) if not lanes_per_program
           else max(1, int(lanes_per_program)))
    for g0 in range(0, len(lanes), spp):
        group = lanes[g0:g0 + spp]
        group_points = list(grid)[g0:g0 + spp]
        t0 = time.time()
        trainer = FleetTrainer(base, ds, lane_configs=group,
                               logger=logger)
        state, out = trainer.fit()
        wall = round(time.time() - t0, 1)
        for i, cfg in enumerate(group):
            best = os.path.join(cfg.train.save_dir, cfg.checkpoint_name())
            params = (load_params(best,
                                  unstack_state(state.params, i))
                      if os.path.isdir(best)
                      else unstack_state(state.params, i))
            rec = _compare_point(cfg, ds, params, ref_scores, labels,
                                 score_start, score_end)
            rec.update(
                lr=group_points[i][0], kl_weight=group_points[i][1],
                best_val=float(out["best_val"][i]),
                # the program wall is SHARED by the whole group — that
                # amortization is the point; recorded per rec so the
                # serial-resume reader finds the key it always had
                train_seconds=wall,
                hyper_fleet=True,
                lanes_per_program=len(group),
            )
            recs.append(rec)
    return recs


def _refresh_diagnosis(path, results, logger) -> None:
    """Merge a `hyper_fleet` provenance block into K60_DIAGNOSIS.json:
    the (kl_weight x lr) loss-balance grid re-raced as ONE program per
    shape bucket. Purely ADDITIVE — every existing key of the diagnosis
    artifact is preserved (resume/readers stay format-compatible)."""
    try:
        with open(path) as f:
            diag = json.load(f)
        if not isinstance(diag, dict):
            raise ValueError("not a JSON object")
    except FileNotFoundError:
        diag = {}
    except (OSError, ValueError) as e:
        logger.log("k60_diag_refresh_skipped", path=path, error=str(e),
                   note="existing diagnosis unreadable; NOT overwriting")
        return
    diag["hyper_fleet"] = {
        "refreshed_by": "parity_k60_sweep.py --hyper",
        "platform": results.get("platform"),
        "epochs": results.get("epochs"),
        "execution": "one hyper-fleet program per shape bucket "
                     "(per-lane (lr, kl_weight) as runtime scalars; "
                     "train/fleet.py lane_configs)",
        "grid": [
            {"lr": r["lr"], "kl_weight": r["kl_weight"],
             "rank_ic": r.get("rank_ic"), "best_val": r.get("best_val"),
             "train_seconds": r.get("train_seconds"),
             "hyper_fleet": bool(r.get("hyper_fleet", False))}
            for r in results.get("grid", [])
        ],
    }
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(diag, f, indent=1)
    os.replace(tmp, path)
    logger.log("k60_diag_refreshed", path=path,
               grid_points=len(diag["hyper_fleet"]["grid"]))


def _parse_points(spec):
    """'1e-4:1.0,3e-4:0.1' -> [(1e-4, 1.0), (3e-4, 0.1)]."""
    out = []
    for tok in spec.split(","):
        lr, klw = tok.split(":")
        out.append((float(lr), float(klw)))
    return out


DEFAULT_GRID = "1e-4:1,1e-4:0.1,1e-4:0.02,3e-4:1,3e-4:0.1,3e-4:0.02"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--scores_dir", default="/root/reference/scores")
    ap.add_argument("--epochs", "--num_epochs", dest="epochs", type=int,
                    default=50,
                    help="epochs per run (--num_epochs is an alias so "
                         "epoch-matched controls can use the CLI's flag "
                         "name)")
    ap.add_argument("--seeds", type=int, default=8)
    ap.add_argument("--fleet", choices=["auto", "on", "off"],
                    default="auto",
                    help="seed-parallel sweep execution (train/fleet.py)."
                         " auto = follow the planner's raced "
                         "seeds_per_program for this shape (serial when "
                         "the plan says 1); on = one program for all "
                         "seeds; off = serial")
    ap.add_argument("--hyper", action="store_true",
                    help="race the grid phase through hyper-fleet "
                         "programs (ISSUE 12): every pending "
                         "(lr, kl_weight) point becomes one LANE of a "
                         "stacked program (train/fleet.py lane_configs) "
                         "— one compile for the whole grid instead of "
                         "one per point. Records keep the serial grid's "
                         "format (resume-compatible), and a completed "
                         "non-quick grid refreshes K60_DIAGNOSIS.json "
                         "with hyper-fleet provenance (--diag)")
    ap.add_argument("--diag", default="K60_DIAGNOSIS.json",
                    help="diagnosis artifact to refresh under --hyper "
                         "(additive `hyper_fleet` block; existing keys "
                         "preserved)")
    ap.add_argument("--lanes_per_program", type=int, default=None,
                    help="lanes per hyper-fleet program under --hyper "
                         "(default: the whole grid in one program, or "
                         "the planner's raced Plan.lanes_per_program "
                         "when a measured hyper row exists)")
    ap.add_argument("--grid", default=DEFAULT_GRID,
                    help="comma-separated lr:kl_weight grid points; "
                         "'' skips the grid phase")
    ap.add_argument("--sweeps", default=None,
                    help="explicit lr:kl_weight sweep targets, run BEFORE "
                         "the grid (CPU-fallback mode: headline CIs "
                         "first, grid points as time allows). Default: "
                         "grid winner + reference-faithful, after the "
                         "grid.")
    ap.add_argument("--out", default="PARITY_RUN_r04.json")
    ap.add_argument("--metrics_jsonl", default=None,
                    help="append progress + per-seed sweep events to this "
                         "JSONL stream (ISSUE 5: one RUN.jsonl per "
                         "session; obs.report renders it)")
    ap.add_argument("--quick", action="store_true",
                    help="2 epochs, 2 seeds, 2 grid points (smoke)")
    args = ap.parse_args(argv)

    from factorvae_tpu.data.loader import PanelDataset
    from factorvae_tpu.presets import get_preset
    from factorvae_tpu.utils.testing import enable_persistent_compile_cache

    enable_persistent_compile_cache()
    # ONE logger/event stream for the whole protocol: every Trainer
    # epoch, seed_sweep per-seed record and [k60] progress line goes
    # through it (raw prints made a full autotune+sweep session
    # unreconstructable; echo keeps the console experience).
    from factorvae_tpu.utils.logging import MetricsLogger

    logger = MetricsLogger(jsonl_path=args.metrics_jsonl, echo=True,
                           run_name="parity_k60_sweep")
    # close-on-error: a multi-hour sweep killed mid-grid must still
    # finalize the JSONL handle (and any wandb run), not just the
    # happy path — the same contract autotune_plan.py's `with` carries.
    try:
        ref = load_ref_scores(args.scores_dir)
        panel, prefix_dates, window_dates = build_proxy_panel(ref)
        labels = panel_labels(panel)
        score_start = str(window_dates[0].date())
        score_end = str(window_dates[-1].date())

        cfg0 = get_preset(PRESET)
        # _cfg_for forces compute_dtype=float32 on every run (presets are
        # bf16 for bench; parity should not fold a dtype change in).
        ds = PanelDataset(panel, seq_len=cfg0.model.seq_len, pad_multiple=8)

        # Fleet execution (train/fleet.py): --fleet auto follows the
        # planner's raced seeds_per_program for this shape; partial-result
        # files stay format-compatible (on_seed fires per seed either way).
        from factorvae_tpu.plan import plan_for_config

        plan = plan_for_config(cfg0, getattr(ds, "n_real", ds.n_max))
        if args.fleet == "on":
            use_fleet, spp = True, None      # one program for all seeds
        elif args.fleet == "off":
            use_fleet, spp = False, None
        else:
            spp = plan.seeds_per_program
            use_fleet = spp > 1
        logger.log(
            "k60_execution",
            mode=("fleet (seeds_per_program=%s)" % (spp or "all")
                  if use_fleet else "serial"),
            plan_provenance=plan.provenance,
            plan_seeds_per_program=plan.seeds_per_program)

        epochs = 2 if args.quick else args.epochs
        n_seeds = 2 if args.quick else args.seeds
        grid = _parse_points(args.grid) if args.grid else []
        if args.quick:
            grid = grid[:2]

        import jax

        from factorvae_tpu.eval.metrics import daily_rank_ic

        ref_joined = ref[PRESET].join(labels.rename("LABEL0"),
                                      how="inner").dropna()
        ref_ic0 = float(daily_rank_ic(ref_joined, "LABEL0", "score").mean())

        results = {"preset": PRESET, "epochs": epochs,
                   "platform": jax.devices()[0].platform,
                   "protocol": "proxy panel (parity_protocol.build_proxy_panel)",
                   "reference_rank_ic": ref_ic0,
                   "complete": False, "grid": [], "sweeps": {}}

        # Restart resume (ADVICE r4): adopt finished records from a prior
        # partial run of the SAME protocol so a killed multi-hour run
        # continues instead of silently redoing every seed. partial_seeds
        # values are full per-seed records (older files stored bare
        # rank_ic floats; seed_sweep accepts both via prior_records).
        if os.path.exists(args.out):
            try:
                with open(args.out) as f:
                    prev = json.load(f)
            except (OSError, json.JSONDecodeError):
                prev = None
            if prev and prev.get("preset") == PRESET \
                    and prev.get("epochs") == epochs \
                    and prev.get("platform") == results["platform"]:
                results["grid"] = prev.get("grid", [])
                results["sweeps"] = prev.get("sweeps", {})
                n_prior = sum(len(s.get("partial_seeds", {}))
                              + len(s.get("per_seed_rank_ic", {}))
                              for s in results["sweeps"].values())
                logger.log("k60_resume", out=args.out,
                           grid_points=len(results["grid"]),
                           adopted_seeds=n_prior)
            elif prev:
                # Do NOT overwrite a finished multi-hour artifact in place:
                # a protocol-mismatched rerun (e.g. --quick smoke against a
                # completed 50-epoch file) moves the old file aside first.
                bak = args.out + ".mismatch.bak"
                n = 1
                while os.path.exists(bak):
                    n += 1
                    bak = f"{args.out}.mismatch.bak{n}"
                shutil.move(args.out, bak)
                # name only the fields that actually mismatch (ADVICE r5) —
                # the CHIP_DAY.log reader should not have to guess which of
                # three candidate causes blocked the resume
                mismatches = [
                    f"{field} {prev.get(field)!r} != {want!r}"
                    for field, want in (("preset", PRESET), ("epochs", epochs),
                                        ("platform", results["platform"]))
                    if prev.get(field) != want
                ]
                logger.log(
                    "k60_resume_mismatch", out=args.out, moved_to=bak,
                    mismatches="; ".join(mismatches),
                    note="starting fresh — CPU seeds must not silently mix "
                         "into a TPU statistics artifact or vice versa")

        def _json_safe(o):
            # Non-finite floats (e.g. NaN rank_ic_ir on seeds resumed from
            # a legacy bare-float partial) would serialize as the
            # non-standard `NaN` token and break strict JSON consumers.
            if isinstance(o, float) and not np.isfinite(o):
                return None
            if isinstance(o, dict):
                return {k: _json_safe(v) for k, v in o.items()}
            if isinstance(o, list):
                return [_json_safe(v) for v in o]
            return o

        def flush():
            # Incremental persistence: a multi-hour CPU-fallback run killed
            # at round end must leave every finished record on disk.
            with open(args.out, "w") as f:
                json.dump(_json_safe(results), f, indent=1)

        def run_point(lr, klw, tag):
            cfg = _cfg_for(cfg0, prefix_dates, window_dates,
                           epochs, lr, klw, tag)
            rec = _run_one(cfg, ds, ref[PRESET], labels,
                           score_start, score_end, logger=logger)
            rec.update(lr=lr, kl_weight=klw)
            return rec

        def sweep(lr, klw, label):
            from factorvae_tpu.eval.sweep import seed_sweep

            # Resume matches by (lr, kl_weight), not display label:
            # explicit --sweeps mode and the grid-winner path name the same
            # point 'lr1e-4_kl1' vs 'winner'/'reference_faithful', and a
            # label miss would retrain a finished multi-hour sweep.
            for lbl, e in results["sweeps"].items():
                if (e.get("lr"), e.get("kl_weight")) == (lr, klw):
                    label = lbl
                    break
            entry = results["sweeps"].get(label, {})
            done = entry.get("per_seed_rank_ic", {})
            if len(done) >= n_seeds:
                logger.log("k60_sweep_skipped", label=label,
                           seeds_done=len(done), seeds_wanted=n_seeds)
                return
            cfg = _cfg_for(cfg0, prefix_dates, window_dates,
                           epochs, lr, klw, f"sweep_{label}")
            shutil.rmtree(cfg.train.save_dir, ignore_errors=True)
            partial = results["sweeps"].setdefault(
                label, {"lr": lr, "kl_weight": klw})
            partial.setdefault("partial_seeds", {})
            # A finished-but-smaller sweep (e.g. 5 seeds, now asked for 8)
            # contributes its seeds as priors rather than being redone.
            for s, v in done.items():
                partial["partial_seeds"].setdefault(s, {
                    "rank_ic": v,
                    "rank_ic_ir": entry.get(
                        "per_seed_rank_ic_ir", {}).get(s, float("nan")),
                    "best_val": entry.get(
                        "per_seed_best_val", {}).get(s, float("nan")),
                })
            prior = dict(partial["partial_seeds"])
            if prior:
                logger.log("k60_sweep_resuming", label=label,
                           seeds_on_disk=len(prior))

            def on_seed(rec):
                partial["partial_seeds"][rec["seed"]] = rec
                flush()

            df = seed_sweep(cfg, ds, seeds=list(range(n_seeds)),
                            score_start=score_start, score_end=score_end,
                            logger=logger, on_seed=on_seed,
                            prior_records=prior,
                            fleet=use_fleet, seeds_per_program=spp)
            s = df.attrs["summary"]
            mean, std, n = s["rank_ic_mean"], s["rank_ic_std"], s["num_seeds"]
            ref_ic = results["reference_rank_ic"]
            ci = 1.96 * std / np.sqrt(max(n, 1))
            rec = {
                "lr": lr, "kl_weight": klw,
                "per_seed_rank_ic": df["rank_ic"].to_dict(),
                "per_seed_rank_ic_ir": df["rank_ic_ir"].to_dict(),
                "per_seed_best_val": df["best_val"].to_dict(),
                **s,
                "ci95_half_width": float(ci),
                "reference_rank_ic": ref_ic,
            }
            if ref_ic:
                rec["recovery_fraction"] = float(mean / ref_ic)
                rec["recovery_ci"] = [float((mean - ci) / ref_ic),
                                      float((mean + ci) / ref_ic)]
            results["sweeps"][label] = rec
            flush()
            logger.log(
                "k60_sweep_done", label=label, mean=round(mean, 4),
                std=round(std, 4), n=n,
                recovery=rec.get("recovery_fraction", float("nan")))

        explicit_sweeps = _parse_points(args.sweeps) if args.sweeps else None
        if explicit_sweeps:
            # CPU-fallback ordering: headline seed-sweep CIs first, grid
            # afterwards as time allows.
            for lr, klw in explicit_sweeps:
                logger.log("k60_explicit_sweep", lr=lr, kl_weight=klw,
                           seeds=n_seeds)
                sweep(lr, klw, f"lr{lr:g}_kl{klw:g}")

        logger.log("k60_grid_start", points=len(grid), epochs=epochs,
                   hyper=args.hyper)
        done_points = {(r["lr"], r["kl_weight"]) for r in results["grid"]}
        pending_grid = [p for p in grid if p not in done_points]
        for lr, klw in grid:
            if (lr, klw) in done_points:
                logger.log("k60_grid_skipped", lr=lr, kl_weight=klw)
        if args.hyper and pending_grid:
            # ONE compiled program for the whole pending grid (bounded
            # by --lanes_per_program / the planner's raced lane width).
            lpp = args.lanes_per_program
            if lpp is None and plan.lanes_per_program > 0:
                lpp = plan.lanes_per_program
            for rec in _run_grid_hyper(
                    cfg0, ds, pending_grid, prefix_dates, window_dates,
                    epochs, ref[PRESET], labels, score_start, score_end,
                    logger, lanes_per_program=lpp):
                results["grid"].append(rec)
                flush()
                logger.log("k60_grid_point", lr=rec["lr"],
                           kl_weight=rec["kl_weight"],
                           rank_ic=rec["rank_ic"],
                           train_seconds=rec["train_seconds"],
                           hyper_fleet=True)
        else:
            for lr, klw in pending_grid:
                rec = run_point(lr, klw, f"lr{lr:g}_kl{klw:g}")
                results["grid"].append(rec)
                flush()
                logger.log("k60_grid_point", lr=lr, kl_weight=klw,
                           rank_ic=rec["rank_ic"],
                           train_seconds=rec["train_seconds"])
        if args.hyper and not args.quick and results["grid"]:
            # Refresh the K-scaling diagnosis artifact with hyper-fleet
            # provenance (additive block; format-compatible).
            _refresh_diagnosis(args.diag, results, logger)

        if not explicit_sweeps and results["grid"]:
            best = max(results["grid"], key=lambda r: r["rank_ic"])
            results["grid_winner"] = {"lr": best["lr"],
                                      "kl_weight": best["kl_weight"]}
            logger.log("k60_winner_sweep", lr=best["lr"],
                       kl_weight=best["kl_weight"], seeds=n_seeds)
            sweep(best["lr"], best["kl_weight"], "winner")
            if (best["lr"], best["kl_weight"]) != (1e-4, 1.0):
                logger.log("k60_reference_faithful_sweep", lr=1e-4,
                           kl_weight=1.0, seeds=n_seeds)
                sweep(1e-4, 1.0, "reference_faithful")

        results["complete"] = True
        flush()
        logger.log("k60_done", out=args.out)
        return 0
    finally:
        logger.finish()


if __name__ == "__main__":
    sys.exit(main())
