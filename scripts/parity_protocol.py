"""Rank-IC parity protocol runner (BASELINE.md protocol; VERDICT r1 item 4).

Real CSI300 market data is unavailable in this sandbox (zero egress, no
qlib bundle), so the REAL-data parity number remains blocked-by-data —
documented in PARITY.md. This driver still executes the complete
protocol mechanically against the reference's shipped ground-truth
artifacts (`/root/reference/scores/free{20,48,60}_*.csv`, 125,539 rows
each; naming per scores/readme.md):

1. Load the three reference score CSVs (the real 2018-12-28→2020-09-23
   window, 356 instruments, ~299/day validity pattern).
2. Build a proxy CSI300-shaped panel whose latent per-(day, stock) alpha
   IN THE SCORE WINDOW is the cross-sectionally z-scored reference K=60
   score itself, embedded linearly in the 158 features; labels are
   `s * alpha + sqrt(1-s^2) * noise` at daily-return scale. So the real
   reference scores genuinely predict the proxy labels (Rank-IC ~= s by
   construction), the real cross-config correlation structure between
   K=20/48/60 is preserved, and a model that recovers alpha from the
   features can match the reference's Rank-IC.
3. Train the csi300-k{20,48,60} presets on the proxy panel, score the
   reference window deterministically, export reference-named CSVs.
4. Run eval/compare.py's join+Rank-IC on (reference CSV, our CSV,
   shared labels) and report the measured delta vs the ±0.002 target,
   plus the mean per-day Spearman between our scores and the
   reference's (score-alignment diagnostic).

Usage:
    python scripts/parity_protocol.py [--epochs 15] [--out PARITY_RUN.json]
        [--scores_dir /root/reference/scores] [--quick]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import shutil
import sys
import time

import numpy as np
import pandas as pd

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REF_CSVS = {
    "csi300-k20": "free20_20_True_False_158_20.csv",
    "csi300-k48": "free48_48_True_False_158_48.csv",
    "csi300-k60": "free60_60_True_False_158_60.csv",
}
ALPHA_SOURCE = "csi300-k60"     # whose scores seed the latent alpha
SIGNAL = 0.08                   # Rank-IC plateau planted in the labels
FEATURE_STRENGTH = 2.0          # alpha amplitude inside the features
LABEL_SCALE = 0.02              # daily-return-like magnitude
PREFIX_DAYS = 800               # training history before the score window
# (the reference protocol is ~2190 train days x 30 epochs = 65k steps at
# lr 1e-4; at this SNR the VAE needs a comparable step count to surface
# the signal — a linear probe on the same panel reaches IC 0.065, the
# r2 first attempt with 440 days x 15 epochs = 6.6k steps reached ~0)


def load_ref_scores(scores_dir: str) -> dict:
    out = {}
    for preset, fname in REF_CSVS.items():
        df = pd.read_csv(os.path.join(scores_dir, fname),
                         parse_dates=["datetime"])
        out[preset] = df.set_index(["datetime", "instrument"]).sort_index()
    return out


def panel_labels(panel) -> pd.Series:
    """The panel's LABEL0 column as a (datetime, instrument)-indexed
    Series over valid rows — the shared join target for every
    proxy-panel Rank-IC computation (this driver, parity_k60_sweep,
    k60_diagnose); keep the layout in ONE place."""
    return pd.Series(
        panel.values[..., -1].T[panel.valid],
        index=pd.MultiIndex.from_arrays(
            [np.repeat(panel.dates, panel.valid.sum(axis=1)),
             np.concatenate([panel.instruments[panel.valid[i]]
                             for i in range(len(panel.dates))])],
            names=["datetime", "instrument"]),
        name="LABEL0")


def zscore_by_day(s: pd.Series) -> pd.Series:
    g = s.groupby(level=0)
    return (s - g.transform("mean")) / g.transform("std").replace(0, np.nan)


def build_proxy_panel(ref: dict, seed: int = 0):
    """Panel whose window alpha = z-scored reference K=60 scores."""
    from factorvae_tpu.data.panel import Panel

    src = ref[ALPHA_SOURCE]["score"]
    window_dates = src.index.get_level_values(0).unique().sort_values()
    instruments = np.sort(src.index.get_level_values(1).unique().to_numpy())
    prefix_dates = pd.bdate_range(
        end=window_dates[0] - pd.Timedelta(days=1), periods=PREFIX_DAYS)
    dates = prefix_dates.append(pd.DatetimeIndex(window_dates))
    d, n, c = len(dates), len(instruments), 158
    p = len(prefix_dates)

    rng = np.random.default_rng(seed)
    # latent alpha: iid in the prefix, z-scored real reference scores in
    # the window (missing (day, stock) pairs stay invalid)
    alpha = rng.normal(size=(n, d)).astype(np.float32)
    valid = np.ones((d, n), bool)

    z = zscore_by_day(src)
    date_pos = pd.Series(np.arange(d), index=dates)
    inst_pos = pd.Series(np.arange(n), index=instruments)
    di = date_pos[z.index.get_level_values(0)].to_numpy()
    ii = inst_pos[z.index.get_level_values(1)].to_numpy()
    window_valid = np.zeros((d, n), bool)
    window_valid[di, ii] = np.isfinite(z.to_numpy())
    valid[p:] = window_valid[p:]
    a = np.zeros((d, n), np.float32)
    a[di, ii] = np.nan_to_num(z.to_numpy()).astype(np.float32)
    alpha[:, p:] = a[p:].T

    w = (rng.normal(size=(c,)) / np.sqrt(c)).astype(np.float32)
    feats = (FEATURE_STRENGTH * alpha[:, :, None] * w[None, None, :]
             + rng.normal(size=(n, d, c)).astype(np.float32))
    noise = rng.normal(size=(n, d)).astype(np.float32)
    label = LABEL_SCALE * (SIGNAL * alpha
                           + np.sqrt(1.0 - SIGNAL**2) * noise)
    values = np.concatenate([feats, label[..., None]], axis=-1)
    values[~valid.T[..., None].repeat(c + 1, -1)] = np.nan

    panel = Panel(values=values, valid=valid, dates=dates,
                  instruments=instruments)
    return panel, prefix_dates, window_dates


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--scores_dir", default="/root/reference/scores")
    ap.add_argument("--epochs", type=int, default=50)
    ap.add_argument("--out", default="PARITY_RUN.json")
    ap.add_argument("--score_dir", default="/tmp/parity_scores")
    ap.add_argument("--quick", action="store_true",
                    help="2 epochs, k20 only (smoke)")
    ap.add_argument("--presets", default=None,
                    help="comma-separated subset (e.g. csi300-k48); "
                         "merges into --out if it already exists")
    ap.add_argument("--sweep_seeds", type=int, default=0,
                    help="additionally run eval.sweep.seed_sweep with "
                         "this many seeds per preset (statistical parity "
                         "per SURVEY §7 hard-part 3)")
    ap.add_argument("--tolerance", type=float, default=0.002)
    args = ap.parse_args(argv)

    from factorvae_tpu.config import Config
    from factorvae_tpu.data.loader import PanelDataset
    from factorvae_tpu.eval.compare import compare_scores
    from factorvae_tpu.eval.metrics import daily_rank_ic
    from factorvae_tpu.eval.predict import (
        export_scores,
        generate_prediction_scores,
    )
    from factorvae_tpu.presets import get_preset
    from factorvae_tpu.train.checkpoint import load_params
    from factorvae_tpu.train.trainer import Trainer
    from factorvae_tpu.utils.logging import MetricsLogger
    from factorvae_tpu.utils.testing import enable_persistent_compile_cache

    enable_persistent_compile_cache()
    ref = load_ref_scores(args.scores_dir)
    panel, prefix_dates, window_dates = build_proxy_panel(ref)
    labels = panel_labels(panel)

    # split: train on the prefix minus a 60-day validation tail
    fit_end = prefix_dates[-61]
    val_start, val_end = prefix_dates[-60], prefix_dates[-1]
    score_start, score_end = window_dates[0], window_dates[-1]

    if args.quick:
        presets = ["csi300-k20"]
    elif args.presets:
        presets = [p.strip() for p in args.presets.split(",")]
    else:
        presets = list(REF_CSVS)
    epochs = 2 if args.quick else args.epochs
    results = {
        "protocol": "BASELINE.md Rank-IC parity (proxy labels)",
        "real_data": False,
        "blocked_by": "no qlib CSI300 bundle in sandbox (zero egress); "
                      "proxy panel seeds the window alpha with the real "
                      "reference K=60 scores",
        "planted_signal": SIGNAL,
        "tolerance": args.tolerance,
        "configs": {},
    }
    for preset_name in presets:
        cfg0 = get_preset(preset_name)
        cfg = Config(
            # Force float32: presets default to bf16 for bench, but the
            # statistics-sensitive parity protocol must not fold a dtype
            # change into its numbers.
            model=dataclasses.replace(cfg0.model, compute_dtype="float32"),
            data=dataclasses.replace(
                cfg0.data,
                dataset_path=None,
                start_time=str(prefix_dates[0].date()),
                fit_end_time=str(fit_end.date()),
                val_start_time=str(val_start.date()),
                val_end_time=str(val_end.date()),
                end_time=str(score_end.date()),
            ),
            train=dataclasses.replace(
                cfg0.train, num_epochs=epochs, checkpoint_every=0,
                save_dir=os.path.join("/tmp/parity_models", preset_name)),
            mesh=cfg0.mesh,
        )
        # fresh best-val dir: never load a stale checkpoint from an
        # earlier protocol run
        shutil.rmtree(cfg.train.save_dir, ignore_errors=True)
        ds = PanelDataset(panel, seq_len=cfg.model.seq_len, pad_multiple=8)
        t0 = time.time()
        trainer = Trainer(cfg, ds, logger=MetricsLogger(echo=False))
        state, out = trainer.fit()
        train_s = time.time() - t0
        # score with the BEST-VALIDATION weights, as the reference's
        # backtest does (backtest.ipynb cell 2) — at K=60 the final-epoch
        # params overfit the proxy panel hard (r2: IC 0.010 final vs
        # best-val selection)
        best = os.path.join(cfg.train.save_dir, cfg.checkpoint_name())
        if os.path.isdir(best):
            params = load_params(best, state.params)
        else:
            print(f"[parity] WARNING: best-val checkpoint missing at "
                  f"{best}; scoring FINAL-epoch params")
            params = state.params
        scores = generate_prediction_scores(
            params, cfg, ds,
            start=str(score_start.date()), end=str(score_end.date()),
            stochastic=False, with_labels=True)
        path = export_scores(scores, cfg, args.score_dir)

        cmp = compare_scores(ref[preset_name], scores[["score"]], labels,
                             tolerance=args.tolerance)
        # score-alignment diagnostic: mean per-day Spearman(ours, ref)
        joined = scores[["score"]].rename(columns={"score": "ours"}).join(
            ref[preset_name]["score"].rename("ref"), how="inner").dropna()
        align = daily_rank_ic(joined, "ref", "ours")
        cmp["score_spearman_to_ref"] = float(align.mean())
        cmp["train_seconds"] = round(train_s, 2)
        cmp["best_val"] = float(out["best_val"])
        cmp["epochs"] = epochs
        cmp["export"] = path
        if args.sweep_seeds:
            from factorvae_tpu.eval.sweep import seed_sweep

            sw = seed_sweep(
                cfg, ds, seeds=list(range(args.sweep_seeds)),
                score_start=str(score_start.date()),
                score_end=str(score_end.date()))
            cmp["seed_sweep"] = {
                "per_seed_rank_ic": sw["rank_ic"].to_dict(),
                **sw.attrs["summary"],
            }
        results["configs"][preset_name] = cmp
        print(f"[parity] {preset_name}: ref_ic={cmp['reference_rank_ic']:.4f} "
              f"ours_ic={cmp['ours_rank_ic']:.4f} "
              f"delta={cmp['delta_rank_ic']:+.4f} "
              f"align={cmp['score_spearman_to_ref']:.3f} "
              f"({train_s:.0f}s train)"
              + (f" sweep_mean={cmp['seed_sweep']['rank_ic_mean']:.4f}"
                 f"±{cmp['seed_sweep']['rank_ic_std']:.4f}"
                 if args.sweep_seeds else ""))

    # Merge ONLY for explicit --presets subset runs (per --presets help);
    # full and --quick runs overwrite so a smoke run can never silently
    # splice 2-epoch results into the authoritative artifact.
    if args.presets and os.path.exists(args.out):
        try:
            with open(args.out) as fh:
                prior = json.load(fh)
            merged_configs = dict(prior.get("configs", {}))
            merged_configs.update(results["configs"])
            prior.update({k: v for k, v in results.items()
                          if k != "configs"})
            prior["configs"] = merged_configs
            results = prior
        except Exception as e:
            print(f"[parity] WARNING: could not merge into existing "
                  f"{args.out} ({type(e).__name__}: {e}); prior configs "
                  f"will be OVERWRITTEN by this subset run")
    with open(args.out, "w") as fh:
        json.dump(results, fh, indent=2)
    print(f"[parity] wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
