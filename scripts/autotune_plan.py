"""Bounded micro-autotune for the execution planner (factorvae_tpu/plan).

Races the candidate execution paths for one or more preset shapes ON THE
CURRENT BACKEND and persists the measured winners as envelope-table rows
(`PLAN_TABLE.json`, env `FACTORVAE_PLAN_TABLE`), so `plan_for` resolves
them with provenance "measured" instead of falling back to the
conservative per-backend default. One command, bounded by construction:

- the candidate set is fixed and small — train races
  {reference-faithful un-flattened dps=1, flattened dps=8} x
  {float32, bfloat16}; scoring races {un-flattened, flattened} x
  {float32, bfloat16} over the single-dispatch scan path — 8 timed
  programs per shape, each on a tiny synthetic panel (default 8 days);
- the conservative default path is ALWAYS in the raced set, so a
  written row is never slower than what the fallback would have run
  (the planner cannot regress a measured shape);
- every candidate timing is stored on the row (`measured`) for audit;
  `plan_for` only reads the winner fields.

Kernel on/off stays "auto" (the per-shape raced envelope in plan.py —
racing interpreted Pallas kernels off-TPU would be meaningless).

`--fleet` additionally races the seed-parallel program width
(`seeds_per_program` in {1, 2, 4, 8}, train/fleet.py) on each shape's
winning train knobs and persists the aggregate-seed-throughput winner
as the row's `fleet` block; S=1 (serial) is always in the raced set, so
a written knob never regresses a multi-seed workload below the serial
path. Rows without a `fleet` block (every pre-fleet table) keep
resolving exactly as before: `plan_for` defaults them to serial.

`--stream` races the panel residency (HBM vs the out-of-core stream
path at several chunk sizes, data/stream.py) on the winning train knobs
and persists the winner as the row's `stream` block
(`Plan.panel_residency` / `Plan.stream_chunk_days`); HBM is always in
the raced set, and rows without the block keep resolving to HBM.

`--kernels` races {pallas, xla} x {gru, attention} forward+backward at
each shape's production operating point on the CURRENT backend
(scripts/race_kernels.py is the timing engine) and persists the
measured verdict as the row's `kernels` block
(`Plan.kernel_gru`/`Plan.kernel_attention` + a `use_pallas_*` pin);
the `plan.pallas_*_wins` predicates read the verdict first, with the
frozen round-2 envelope constants demoted to the no-row fallback. XLA
is always in the raced set, so a persisted verdict can never regress a
shape below the fallback path. Off-TPU the pallas legs run in
interpret mode — honest (enormous) walls that correctly pin XLA.

`--remat` races the rematerialization rung (train/loop.py
`jax.checkpoint`) on the winning train knobs: remat in
{none, dots, full}, judged on wall-clock AND on whether the freed
`peak_bytes` (obs/compile.guarded_memory_analysis) admits a LARGER
days_per_step that wins end-to-end. "none" (the exact pre-remat graph)
is always raced; a non-none rung persists as the row's `train_remat`
block ONLY past a measured per-day wall-clock win — the gate ROADMAP
item 3 asks for.

Usage:
    python scripts/autotune_plan.py                       # flagship shape
    python scripts/autotune_plan.py --config csi300-k60
    python scripts/autotune_plan.py --all                 # every preset shape
    python scripts/autotune_plan.py --all --days 4 --reps 1   # quickest
    python scripts/autotune_plan.py --fleet               # + fleet knob race
    python scripts/autotune_plan.py --stream              # + residency race
    python scripts/autotune_plan.py --mesh                # + mesh-shape race
    python scripts/autotune_plan.py --serve               # + precision ladder
    python scripts/autotune_plan.py --train_precision     # + training ladder
    python scripts/autotune_plan.py --kernels             # + kernel race
    python scripts/autotune_plan.py --remat               # + remat race
        [--out PLAN_TABLE.json] [--dry_run] [--metrics_jsonl RUN.jsonl]

`--serve` races the serving-precision ladder (f32/bf16/int8) through
the model-registry scoring path (serve/registry.py) on the winning
score layout; a sub-f32 winner persists as the row's `serve` block
(`Plan.serve_precision`) ONLY when its measured rank fidelity vs f32
clears the floor — rows without the block serve float32, bitwise the
offline scan.

`--train_precision` races the TRAINING-precision ladder (ISSUE 16):
the f32 oracle vs the bf16 mixed master-weight path
(train/state.py — f32 masters, one bf16 compute cast, dynamic loss
scaling), trained short from one init and each scored through the
deterministic f32 scan. A bf16 winner persists as the row's
`train_precision` block (`Plan.train_compute_dtype`) ONLY when it is
faster AND its trained model's masked-Spearman Rank-IC correlation vs
the f32-trained model clears the floor — rows without the block leave
`TrainConfig.compute_dtype` alone (f32 oracle behavior preserved).

Race progress is emitted as structured events through MetricsLogger
(echoed to stderr; stdout stays the table-JSON artifact). With
`--metrics_jsonl RUN.jsonl` the events land in the same stream a
subsequent `cli.py --metrics_jsonl RUN.jsonl` / sweep run appends to —
one coherent RUN.jsonl for the whole autotune+train+sweep session,
renderable by `python -m factorvae_tpu.obs.report`.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
# --kernels reuses the round-2 chip-race timing engine (same oracles,
# same one-compile-per-candidate jits) from the sibling script.
sys.path.insert(0, os.path.join(REPO, "scripts"))

# Preset-shaped race configs (shapes per factorvae_tpu/presets.py; real
# cross-section widths — the pad policy decides the padded width).
# `stocks` may be a list: each width is raced as its own measured point,
# and adjacent points with IDENTICAL winners merge into one
# [n_min, n_max] envelope row (the kernel-envelope precedent: both
# bounds measured, no extrapolation beyond them). The flagship races
# both its benchmark widths — 300 (bench_reference_cpu / the torch
# head-to-head) and 356 (bench.py / the reference score CSVs) — so a
# fresh autotune covers the shape bench.py actually resolves.
SHAPES = {
    "flagship": dict(stocks=[300, 356], features=158, seq_len=20, hidden=64,
                     factors=96, portfolios=128),
    "csi300-k60": dict(stocks=300, features=158, seq_len=20, hidden=60,
                       factors=60, portfolios=128),
    "csi800-k60": dict(stocks=800, features=158, seq_len=20, hidden=60,
                       factors=60, portfolios=128),
    "alpha360-k60": dict(stocks=300, features=360, seq_len=60, hidden=60,
                         factors=60, portfolios=128),
}

# The bounded candidate grid. (flatten_days, days_per_step) pairs: the
# two layouts that exist; dps rides the layout (un-flattened dps=8 and
# flattened dps=1 are dominated operating points — see PERF.md r05).
TRAIN_CANDIDATES = [
    {"flatten_days": False, "days_per_step": 1},
    {"flatten_days": True, "days_per_step": 8},
]
DTYPES = ["float32", "bfloat16"]
SCORE_CANDIDATES = [{"flatten_days": f} for f in (False, True)]
# --fleet: seed-parallel program widths raced on top of the winning
# train knobs (train/fleet.py). S=1 is the serial path itself, so the
# persisted winner can never be slower than what the fallback runs.
FLEET_CANDIDATES = [1, 2, 4, 8]
# --hyper: heterogeneous-lane program widths (ISSUE 12) raced on the
# winning train knobs — each candidate trains S DISTINCT (lr, kl_weight)
# configs in one hyper-fleet program (train/fleet.py lane_configs; the
# lane scalars are deterministic spreads around the config's defaults,
# so the race is reproducible). S=1 folds to the serial trace, so the
# persisted winner can never regress a grid below the serial sweep.
HYPER_CANDIDATES = [1, 2, 4, 8]
# --stream: panel-residency race on the winning train knobs — HBM vs
# the out-of-core stream path at several chunk sizes (days per
# host->device transfer, data/stream.py). HBM is always in the raced
# set, so a persisted row can never regress an in-memory workload.
STREAM_CHUNK_CANDIDATES = [16, 32, 64]
# --serve: serving-precision ladder raced through the registry scoring
# path (serve/registry.py; ISSUE 8) on the winning SCORE knobs. f32 is
# always in the raced set (it IS the offline scan, bitwise), and a
# lower rung only wins when its measured per-day Spearman rank
# correlation vs f32 clears the floor — serving speed must not buy
# rank-order corruption the backtest would feel. bench_int8_scoring.py
# is a thin shim over the same race (one variant per rung).
SERVE_PRECISIONS = ["float32", "bfloat16", "int8"]
SERVE_FIDELITY_FLOOR = 0.99
# --train_precision: TRAINING-precision ladder (ISSUE 16,
# train/state.py resolve_train_dtype) raced as short f32 vs bf16
# mixed-master-weight trainings from the same init on the winning train
# knobs, each scored deterministically through the f32 scan. The bf16
# rung persists (row "train_precision" block -> Plan.train_compute_dtype)
# only when it is BOTH faster (the main race's measured rates) AND its
# trained model's mean per-day Spearman Rank-IC correlation vs the f32
# oracle's clears this floor. The floor is lower than the serve gate's:
# training noise compounds across steps, so two short trainings diverge
# far more than one activation cast — 0.80 on a short synthetic run
# still pins rank ORDER agreement while tolerating trajectory drift.
TRAIN_FIDELITY_FLOOR = 0.80
TRAIN_PRECISION_EPOCHS = 3
# --remat: rematerialization rungs (train/loop.py jax.checkpoint; ISSUE
# 19, closing ROADMAP item 3) raced on the winning train knobs. "none"
# is the exact pre-remat graph and always raced; a rung that frees
# peak_bytes vs "none" additionally races a DOUBLED days_per_step (the
# batch the freed memory admits) — a rung persists only when some
# operating point of it beats "none" per trained day.
REMAT_CANDIDATES = ["none", "dots", "full"]
# --serve also races the continuous-batching scheduler window
# (serve/daemon.TickScheduler, ISSUE 15) under a closed-loop
# concurrent client load at the winning rung: how long an under-full
# tick holds open for late arrivals. 0 (dispatch immediately) is
# always in the raced set, so a persisted window can never regress a
# low-concurrency deployment below the immediate path; the winner
# lands in the row's `serve` block as `tick_ms`/`max_tick_batch`
# (plan_for -> Plan.serve_tick_ms / Plan.serve_max_tick_batch).
SERVE_TICK_CANDIDATES = [0.0, 2.0, 10.0]
SERVE_TICK_CLIENTS = 4
SERVE_TICK_MAX_BATCH = 64
# --mesh: mesh-shape race on the winning train knobs — every
# (data x stock) factorization of the visible devices, with the no-mesh
# serial path always in the raced set (a persisted "mesh" block can
# never regress a single-device workload; no block is written when
# no-mesh wins). Winners persist as the row's `mesh` block
# (plan_for -> Plan.mesh_data_axis/mesh_stock_axis; rows without the
# block keep the run's own MeshConfig).


def _log(logger, event: str, **fields) -> None:
    """Race progress goes through the metrics/event stream (ISSUE 5: an
    autotune + sweep run should yield ONE coherent RUN.jsonl, not a
    stderr transcript). The echo lands on stderr — stdout is reserved
    for the table JSON artifact. `logger=None` (library callers) falls
    back to a bare stderr line so the functions stay usable standalone."""
    if logger is not None:
        logger.log(event, **fields)
    else:
        shown = ", ".join(f"{k}={v}" for k, v in fields.items())
        print(f"[{event}] {shown}", file=sys.stderr)


def _setup(shape: dict, dtype: str, flatten: bool, dps: int, days: int,
           residency: str = "hbm", chunk_days: int = 32):
    from factorvae_tpu.config import (
        Config, DataConfig, ModelConfig, TrainConfig,
    )
    from factorvae_tpu.data import PanelDataset, synthetic_panel_dense
    from factorvae_tpu.plan import pad_target_policy

    cfg = Config(
        model=ModelConfig(
            num_features=shape["features"], hidden_size=shape["hidden"],
            num_factors=shape["factors"],
            num_portfolios=shape["portfolios"], seq_len=shape["seq_len"],
            compute_dtype=dtype, flatten_days=flatten,
        ),
        data=DataConfig(seq_len=shape["seq_len"], start_time=None,
                        fit_end_time=None, val_start_time=None,
                        val_end_time=None, panel_residency=residency,
                        stream_chunk_days=chunk_days),
        train=TrainConfig(num_epochs=1, days_per_step=dps, seed=0,
                          checkpoint_every=0,
                          save_dir="/tmp/factorvae_autotune"),
    )
    panel = synthetic_panel_dense(
        num_days=days, num_instruments=shape["stocks"],
        num_features=shape["features"])
    ds = PanelDataset(panel, seq_len=shape["seq_len"],
                      max_stocks=pad_target_policy(shape["stocks"]),
                      residency=residency)
    return cfg, ds


def time_train(shape: dict, dtype: str, flatten: bool, dps: int,
               days: int, reps: int) -> tuple:
    """(seconds per trained day, warmup seconds) for one candidate.
    The timed rate excludes compilation as always; the warmup wall —
    compile + first epoch — is the candidate's compile-cost provenance
    (ISSUE 7: a raced winner should say what it costs to BUILD, not
    just to run; with a timeline installed the watchdog's per-miss
    `compile` records land in the same RUN.jsonl for the full split)."""
    import jax

    from factorvae_tpu.train import Trainer
    from factorvae_tpu.utils.logging import MetricsLogger

    cfg, ds = _setup(shape, dtype, flatten, dps, days)
    trainer = Trainer(cfg, ds, logger=MetricsLogger(echo=False))
    state = trainer.init_state()
    t_w = time.time()
    state, m = trainer._train_epoch(state, trainer._epoch_orders(0))  # warmup
    jax.block_until_ready(m["loss"])
    warmup = time.time() - t_w
    # With a timeline installed the watchdog's post-miss capture replay
    # runs INSIDE the external warmup window and would inflate the
    # number; the watchdog's own wall_s brackets exactly the jit call
    # (compile + first execution, capture excluded) — prefer it.
    cap = getattr(trainer._train_epoch_jit, "last_compile", None)
    if cap and cap.get("wall_s"):
        warmup = float(cap["wall_s"])
    t0 = time.time()
    for e in range(1, 1 + reps):
        state, m = trainer._train_epoch(state, trainer._epoch_orders(e))
    jax.block_until_ready(m["loss"])
    return (time.time() - t0) / (reps * days), warmup


def time_score(shape: dict, dtype: str, flatten: bool,
               days: int, reps: int) -> float:
    """Windows/second for one deterministic scoring candidate (the scan
    path — the production eval/predict.py default)."""
    from factorvae_tpu.eval.predict import predict_panel
    from factorvae_tpu.train import Trainer
    from factorvae_tpu.utils.logging import MetricsLogger

    cfg, ds = _setup(shape, dtype, flatten, dps=1, days=days)
    trainer = Trainer(cfg, ds, logger=MetricsLogger(echo=False))
    state = trainer.init_state()
    day_idx = ds.split_days(None, None)
    chunk = min(16, len(day_idx))
    predict_panel(state.params, cfg, ds, day_idx, stochastic=False,
                  chunk=chunk)  # warmup/compile
    t0 = time.time()
    for _ in range(reps):
        predict_panel(state.params, cfg, ds, day_idx, stochastic=False,
                      chunk=chunk)
    dt = time.time() - t0
    return reps * days * shape["stocks"] / dt


def time_fleet(shape: dict, train_knobs: dict, num_seeds: int,
               days: int, reps: int) -> float:
    """Aggregate seed-throughput (windows/sec·seed summed over the
    fleet) for one seed-parallel program width, on the winning train
    knobs (compile excluded)."""
    import jax

    from factorvae_tpu.train.fleet import FleetTrainer
    from factorvae_tpu.utils.logging import MetricsLogger

    cfg, ds = _setup(shape, train_knobs["compute_dtype"],
                     train_knobs["flatten_days"],
                     train_knobs["days_per_step"], days)
    trainer = FleetTrainer(cfg, ds, seeds=list(range(num_seeds)),
                           logger=MetricsLogger(echo=False))
    # init_run_state: at S=1 this is the RAW serial state, so the
    # baseline the race normalizes against pays exactly what the
    # serial Trainer pays (no stack/unstack overhead biasing the
    # persisted winner toward S>1).
    state = trainer.init_run_state()
    state, m = trainer._run_train_epoch(state, 0)  # warmup/compile
    jax.block_until_ready(m["loss"])
    t0 = time.time()
    for e in range(1, 1 + reps):
        state, m = trainer._run_train_epoch(state, e)
    jax.block_until_ready(m["loss"])
    dt = time.time() - t0
    return reps * days * shape["stocks"] * num_seeds / dt


def hyper_lane_spread(cfg, num_lanes: int) -> list:
    """Deterministic heterogeneous lane configs around a base Config:
    lane i races (lr * 1.25**i, kl_weight * 0.5**i) at seed i with a
    tagged run_name — a reproducible stand-in for a real grid, wide
    enough that XLA cannot constant-fold the lanes back together."""
    import dataclasses

    return [
        dataclasses.replace(
            cfg,
            model=dataclasses.replace(
                cfg.model, kl_weight=cfg.model.kl_weight * (0.5 ** i)),
            train=dataclasses.replace(
                cfg.train, seed=i, lr=cfg.train.lr * (1.25 ** i),
                run_name=f"{cfg.train.run_name}_hl{i}"),
        )
        for i in range(num_lanes)
    ]


def time_hyper(shape: dict, train_knobs: dict, num_lanes: int,
               days: int, reps: int) -> float:
    """Aggregate config-throughput (windows/sec·config summed over the
    lanes) for one hyper-fleet program width on the winning train knobs
    (compile excluded — compile AMORTIZATION is bench.py --hyper's
    story; this race sizes the steady-state program width)."""
    import jax

    from factorvae_tpu.train.fleet import FleetTrainer
    from factorvae_tpu.utils.logging import MetricsLogger

    cfg, ds = _setup(shape, train_knobs["compute_dtype"],
                     train_knobs["flatten_days"],
                     train_knobs["days_per_step"], days)
    trainer = FleetTrainer(cfg, ds,
                           lane_configs=hyper_lane_spread(cfg, num_lanes),
                           logger=MetricsLogger(echo=False))
    state = trainer.init_run_state()
    state, m = trainer._run_train_epoch(state, 0)  # warmup/compile
    jax.block_until_ready(m["loss"])
    t0 = time.time()
    for e in range(1, 1 + reps):
        state, m = trainer._run_train_epoch(state, e)
    jax.block_until_ready(m["loss"])
    dt = time.time() - t0
    return reps * days * shape["stocks"] * num_lanes / dt


def race_hyper(name: str, shape: dict, train_knobs: dict,
               days: int, reps: int, logger=None) -> dict:
    """Race `lanes_per_program` over HYPER_CANDIDATES (heterogeneous
    (lr, kl_weight) lanes, train/fleet.py hyper trace); return the
    row's `hyper` block (winner + every candidate timing for audit)."""
    measured = {}
    best_s, best_wps = 1, None
    for s in HYPER_CANDIDATES:
        wps = time_hyper(shape, train_knobs, s, days, reps)
        measured[f"S={s}"] = round(wps, 1)
        _log(logger, "autotune_hyper_candidate", shape=name, lanes=s,
             aggregate_windows_per_sec_config=round(wps, 1))
        if best_wps is None or wps > best_wps:
            best_s, best_wps = s, wps
    return {
        "lanes_per_program": best_s,
        "measured": measured,
        "source": f"hyper race on {train_knobs['compute_dtype']} "
                  f"flat={int(train_knobs['flatten_days'])} "
                  f"dps{train_knobs['days_per_step']}: best S={best_s} "
                  f"at {best_wps:,.0f} w/s·config",
    }


def time_stream(shape: dict, train_knobs: dict, residency: str,
                chunk_days: int, days: int, reps: int) -> float:
    """Seconds per trained day for one residency candidate on the
    winning train knobs (compile excluded)."""
    import jax

    from factorvae_tpu.train import Trainer
    from factorvae_tpu.utils.logging import MetricsLogger

    cfg, ds = _setup(shape, train_knobs["compute_dtype"],
                     train_knobs["flatten_days"],
                     train_knobs["days_per_step"], days,
                     residency=residency, chunk_days=chunk_days)
    trainer = Trainer(cfg, ds, logger=MetricsLogger(echo=False))
    state = trainer.init_state()
    state, m = trainer._train_epoch(state, trainer._epoch_orders(0))  # warmup
    jax.block_until_ready(m["loss"])
    t0 = time.time()
    for e in range(1, 1 + reps):
        state, m = trainer._train_epoch(state, trainer._epoch_orders(e))
    jax.block_until_ready(m["loss"])
    return (time.time() - t0) / (reps * days)


def race_stream(name: str, shape: dict, train_knobs: dict,
                days: int, reps: int, logger=None) -> dict:
    """Race panel residency (hbm vs stream x chunk sizes); return the
    row's `stream` block (winner + every candidate timing for audit)."""
    measured = {}
    candidates = [("hbm", 0)] + [("stream", c)
                                 for c in STREAM_CHUNK_CANDIDATES]
    best, best_sec = ("hbm", 0), None
    for residency, chunk in candidates:
        sec = time_stream(shape, train_knobs, residency, chunk or 32,
                          days, reps)
        key = residency if residency == "hbm" else f"stream_c{chunk}"
        measured[key] = round(sec, 5)
        _log(logger, "autotune_stream_candidate", shape=name,
             candidate=key, s_per_day=round(sec, 5))
        if best_sec is None or sec < best_sec:
            best, best_sec = (residency, chunk), sec
    return {
        "panel_residency": best[0],
        "chunk_days": best[1] or 32,
        "measured": measured,
        "source": f"residency race on {train_knobs['compute_dtype']} "
                  f"flat={int(train_knobs['flatten_days'])} "
                  f"dps{train_knobs['days_per_step']}: best "
                  f"{best[0]}{f' c{best[1]}' if best[0] == 'stream' else ''}"
                  f" at {best_sec:.4f} s/day",
    }


def _rank_corr(a, b) -> float:
    """Mean per-day Spearman rank correlation between two (D, N_max)
    score grids (NaN = padding), through `ops.stats.masked_spearman` —
    average-rank (scipy) semantics, the SAME statistic eval/metrics
    RankIC consumes. Tie handling matters exactly here: int8
    quantization coarsens scores and CREATES ties, and argsort-based
    ranking would break them arbitrarily, biasing the fidelity number
    the SERVE_FIDELITY_FLOOR gates."""
    import jax.numpy as jnp
    import numpy as np

    from factorvae_tpu.ops.stats import masked_spearman

    cs = []
    for i in range(a.shape[0]):
        v = np.isfinite(a[i]) & np.isfinite(b[i])
        if v.sum() < 3:
            continue
        c = float(masked_spearman(
            jnp.asarray(np.nan_to_num(a[i]), jnp.float32),
            jnp.asarray(np.nan_to_num(b[i]), jnp.float32),
            jnp.asarray(v)))
        if np.isfinite(c):
            cs.append(c)
    return float(np.mean(cs)) if cs else float("nan")


def race_serve(name: str, shape: dict, score_knobs: dict,
               days: int, reps: int, logger=None) -> dict:
    """Race the serving-precision ladder (f32 -> bf16 -> int8) through
    the registry scoring path on the winning SCORE layout; return the
    row's `serve` block. A rung is only eligible when its measured
    rank fidelity vs float32 clears SERVE_FIDELITY_FLOOR — f32 (the
    bitwise offline scan) is always eligible, so a persisted winner can
    never corrupt rank order past the documented floor."""
    from factorvae_tpu.serve.registry import ModelRegistry
    from factorvae_tpu.train import Trainer
    from factorvae_tpu.utils.logging import MetricsLogger

    cfg, ds = _setup(shape, "float32", score_knobs["flatten_days"],
                     dps=1, days=days)
    state = Trainer(cfg, ds, logger=MetricsLogger(echo=False)).init_state()
    day_idx = ds.split_days(None, None)
    reg = ModelRegistry()
    measured: dict = {}
    fidelity: dict = {}
    baseline = None
    best, best_wps = "float32", None
    for prec in SERVE_PRECISIONS:
        key = reg.register_params(state.params, cfg, precision=prec)
        reg.score(key, ds, day_idx)  # warmup/compile
        t0 = time.time()
        for _ in range(reps):
            out = reg.score(key, ds, day_idx)
        wps = reps * len(day_idx) * shape["stocks"] / (time.time() - t0)
        if prec == "float32":
            baseline = out
            corr = 1.0
        else:
            corr = _rank_corr(out, baseline)
        measured[prec] = round(wps, 1)
        fidelity[prec] = round(corr, 4)
        _log(logger, "autotune_serve_candidate", shape=name,
             precision=prec, windows_per_sec=round(wps, 1),
             rank_fidelity=round(corr, 4))
        eligible = corr == corr and corr >= SERVE_FIDELITY_FLOOR
        if eligible and (best_wps is None or wps > best_wps):
            best, best_wps = prec, wps
    tick_block = race_serve_tick(name, cfg, state.params, reg, ds,
                                 day_idx, best, reps, logger=logger)
    return {
        "precision": best,
        "tick_ms": tick_block["tick_ms"],
        "max_tick_batch": tick_block["max_tick_batch"],
        "measured": measured,
        "fidelity": fidelity,
        "tick_measured": tick_block["measured"],
        "source": f"serve precision race on score "
                  f"flat={int(score_knobs['flatten_days'])}: best {best} "
                  f"at {best_wps:,.0f} w/s (rank-fidelity floor "
                  f"{SERVE_FIDELITY_FLOOR}); {tick_block['source']}",
    }


def race_train_precision(name: str, shape: dict, train_knobs: dict,
                         train_rates: dict, days: int, reps: int,
                         logger=None) -> dict:
    """Race the TRAINING-precision ladder — the f32 oracle vs the bf16
    mixed master-weight path (train/state.py) — on the winning train
    knobs; return the row's `train_precision` block.

    Same discipline as `race_serve`: f32 is always eligible (it IS the
    serial oracle), and bf16 only wins when (a) its measured training
    rate at the winning (flatten, dps) — already timed by the main
    race — beats f32's, and (b) the model it TRAINS, scored through the
    deterministic f32 scan, keeps a mean per-day `masked_spearman` rank
    correlation vs the f32-trained model at or above
    TRAIN_FIDELITY_FLOOR. Trained-model fidelity (not an activation
    corr) is the right gate here: training noise compounds across
    steps, so only the end-to-end trained artifact says whether bf16
    training preserved the rank signal the backtest consumes."""
    import dataclasses as _dc

    import jax

    from factorvae_tpu.eval.predict import predict_panel
    from factorvae_tpu.train import Trainer
    from factorvae_tpu.utils.logging import MetricsLogger

    flat = bool(train_knobs["flatten_days"])
    dps = int(train_knobs["days_per_step"])
    epochs = max(TRAIN_PRECISION_EPOCHS, reps)
    grids: dict = {}
    rates: dict = {}
    for dtype in DTYPES:
        # The MODEL stays f32 — scoring must run the f32 scan for both
        # rungs so the fidelity number isolates what TRAINING at bf16
        # did to the weights, not what scoring at bf16 does to
        # activations; train.compute_dtype alone selects the rung
        # (resolve_train_dtype). Same seed => bit-identical inits.
        cfg, ds = _setup(shape, "float32", flat, dps, days)
        cfg = _dc.replace(cfg, train=_dc.replace(
            cfg.train, compute_dtype=dtype))
        trainer = Trainer(cfg, ds, logger=MetricsLogger(echo=False))
        state = trainer.init_state()
        for e in range(epochs):
            state, m = trainer._train_epoch(state,
                                            trainer._epoch_orders(e))
        jax.block_until_ready(m["loss"])
        day_idx = ds.split_days(None, None)
        grids[dtype] = predict_panel(
            state.params, cfg, ds, day_idx, stochastic=False,
            chunk=min(16, len(day_idx)))
        rates[dtype] = train_rates.get(
            f"flat={int(flat)}_dps{dps}_{dtype}")
    corr = _rank_corr(grids["bfloat16"], grids["float32"])
    f32_s, bf16_s = rates.get("float32"), rates.get("bfloat16")
    faster = (f32_s is not None and bf16_s is not None
              and bf16_s < f32_s)
    eligible = corr == corr and corr >= TRAIN_FIDELITY_FLOOR
    best = "bfloat16" if (eligible and faster) else "float32"
    _log(logger, "autotune_train_precision_candidate", shape=name,
         rank_fidelity=(round(corr, 4) if corr == corr else None),
         f32_s_per_day=f32_s, bf16_s_per_day=bf16_s,
         bf16_fidelity_ok=bool(eligible), bf16_faster=bool(faster),
         winner=best)
    return {
        "precision": best,
        "fidelity": (round(corr, 4) if corr == corr else None),
        "measured": {"s_per_day": {"float32": f32_s,
                                   "bfloat16": bf16_s},
                     "fidelity": (round(corr, 4) if corr == corr
                                  else None),
                     "epochs": epochs},
        "source": (f"train-precision race (epochs={epochs}, "
                   f"Rank-IC floor {TRAIN_FIDELITY_FLOOR}): "
                   "bf16 fidelity "
                   + (f"{corr:.4f}" if corr == corr else "nan")
                   + f", winner {best}"),
    }


def race_kernels_block(name: str, shape: dict, train_knobs: dict,
                       reps: int, logger=None) -> dict:
    """Race {pallas, xla} x {gru, attention} forward+backward at this
    shape's production operating point on the current backend and
    return the row's `kernels` block: a measured per-rig verdict the
    `plan.pallas_*_wins` predicates read FIRST (the frozen round-2
    constants demote to the no-row fallback — docs/kernels.md).

    The GRU is raced at the row count the winning layout actually
    feeds it (pad_target x days_per_step under cross-day flattening —
    the r3 operating point the static envelope never covered); the
    attention at (pad_target, H, K). The fwd+bwd wall decides: this
    race serves the TRAINING path (ROADMAP item 3), where every kernel
    runs under jax.grad. XLA is always in the raced set, so the
    persisted verdict can never regress a shape below the fallback.
    Off-TPU the pallas legs run in interpret mode — honest (enormous)
    walls that correctly pin XLA for that rig's rows."""
    import jax

    from race_kernels import race_attention, race_gru

    from factorvae_tpu.plan import pad_target_policy

    backend = jax.default_backend()
    pad = pad_target_policy(int(shape["stocks"]))
    gru_rows = (pad * int(train_knobs["days_per_step"])
                if train_knobs["flatten_days"] else pad)
    g = race_gru(gru_rows, shape["seq_len"], shape["hidden"], reps)
    _log(logger, "autotune_kernel_candidate", shape=name, op="gru",
         n=gru_rows, t=shape["seq_len"], h=shape["hidden"],
         pallas_fwdbwd_us=g["pallas_fwdbwd_us"],
         xla_fwdbwd_us=g["xla_fwdbwd_us"])
    a = race_attention(pad, shape["hidden"], shape["factors"], reps)
    _log(logger, "autotune_kernel_candidate", shape=name, op="attention",
         n=pad, h=shape["hidden"], k=shape["factors"],
         pallas_fwdbwd_us=a["pallas_fwdbwd_us"],
         xla_fwdbwd_us=a["xla_fwdbwd_us"])
    gru_win = ("pallas" if g["pallas_fwdbwd_us"] < g["xla_fwdbwd_us"]
               else "xla")
    attn_win = ("pallas" if a["pallas_fwdbwd_us"] < a["xla_fwdbwd_us"]
                else "xla")
    return {
        "gru": gru_win,
        "attention": attn_win,
        "measured": {"backend": backend, "gru": g, "attention": a},
        "source": (f"kernel race on {backend} (fwd+bwd wall): "
                   f"gru[n={gru_rows}] {gru_win} "
                   f"({g['fwdbwd_speedup']}x xla/pallas), "
                   f"attention[n={pad}] {attn_win} "
                   f"({a['fwdbwd_speedup']}x xla/pallas)"),
    }


def _time_remat(shape: dict, train_knobs: dict, remat: str, dps: int,
                days: int, reps: int) -> tuple:
    """(seconds per trained day, compiled peak_bytes) for one
    (remat, days_per_step) operating point on the winning train knobs
    (compile excluded from the rate). The memory bill comes from the
    compiled program itself (capture_compile ->
    guarded_memory_analysis), not a heuristic."""
    import dataclasses as _dc

    import jax

    from factorvae_tpu.obs import compile as compilelib
    from factorvae_tpu.train import Trainer
    from factorvae_tpu.utils.logging import MetricsLogger

    cfg, ds = _setup(shape, train_knobs["compute_dtype"],
                     train_knobs["flatten_days"], dps, days)
    cfg = _dc.replace(cfg, train=_dc.replace(cfg.train, remat=remat))
    trainer = Trainer(cfg, ds, logger=MetricsLogger(echo=False))
    state = trainer.init_state()
    cap = compilelib.capture_compile(
        trainer._train_epoch_jit,
        compilelib.abstractify((state, trainer._epoch_orders(0),
                                trainer.panel_args())))
    peak = int(cap.get("peak_bytes") or 0)
    state, m = trainer._train_epoch(state, trainer._epoch_orders(0))
    jax.block_until_ready(m["loss"])
    t0 = time.time()
    for e in range(1, 1 + reps):
        state, m = trainer._train_epoch(state, trainer._epoch_orders(e))
    jax.block_until_ready(m["loss"])
    return (time.time() - t0) / (reps * days), peak


def race_remat(name: str, shape: dict, train_knobs: dict, days: int,
               reps: int, logger=None) -> dict:
    """Race the rematerialization rung (ISSUE 19, train/loop.py
    `jax.checkpoint` wrapping via TrainConfig.remat) on the winning
    train knobs; return the row's `train_remat` verdict.

    Judged on wall-clock AND on memory-admits-a-larger-batch: a rung
    whose compiled program frees peak_bytes vs "none" additionally
    races a doubled days_per_step — freed memory only counts as a win
    when the bigger batch it admits is faster END-TO-END (per trained
    day), not merely smaller. "none" is always in the raced set, so
    the caller persists a rung only past a measured per-day win (the
    ROADMAP item 3 gate); race_shape writes NO block when "none" wins,
    and rows without the block keep TrainConfig.remat's own default."""
    base_dps = int(train_knobs["days_per_step"])
    measured: dict = {}
    peaks: dict = {}
    best, best_sec = ("none", base_dps), None
    for remat in REMAT_CANDIDATES:
        sec, peak = _time_remat(shape, train_knobs, remat, base_dps,
                                days, reps)
        measured[remat] = {"s_per_day": round(sec, 5),
                           "peak_bytes": peak}
        peaks[remat] = peak
        _log(logger, "autotune_remat_candidate", shape=name, remat=remat,
             days_per_step=base_dps, s_per_day=round(sec, 5),
             peak_bytes=peak)
        if best_sec is None or sec < best_sec:
            best, best_sec = (remat, base_dps), sec
    bigger = base_dps * 2
    if bigger <= days:
        for remat in REMAT_CANDIDATES[1:]:
            if not (peaks.get(remat) and peaks.get("none")
                    and peaks[remat] < peaks["none"]):
                continue
            sec, peak = _time_remat(shape, train_knobs, remat, bigger,
                                    days, reps)
            key = f"{remat}_dps{bigger}"
            measured[key] = {"s_per_day": round(sec, 5),
                             "peak_bytes": peak}
            _log(logger, "autotune_remat_candidate", shape=name,
                 remat=remat, days_per_step=bigger,
                 s_per_day=round(sec, 5), peak_bytes=peak)
            if sec < best_sec:
                best, best_sec = (remat, bigger), sec
    freed = {r: (round(1.0 - peaks[r] / peaks["none"], 4)
                 if peaks.get("none") else None)
             for r in REMAT_CANDIDATES[1:] if r in peaks}
    measured["peak_reduction_frac"] = freed
    return {
        "remat": best[0],
        "days_per_step": best[1],
        "measured": measured,
        "source": (f"remat race on {train_knobs['compute_dtype']} "
                   f"flat={int(train_knobs['flatten_days'])} "
                   f"dps{base_dps} (peak cut dots="
                   f"{freed.get('dots')}, full={freed.get('full')}): "
                   f"best {best[0]} dps{best[1]} at "
                   f"{best_sec:.4f} s/day"),
    }


def race_serve_tick(name: str, cfg, params, reg, ds, day_idx,
                    precision: str, reps: int, logger=None) -> dict:
    """Race the continuous-batching window (TickScheduler's tick_ms)
    under a closed-loop concurrent client load: SERVE_TICK_CLIENTS
    threads hammer two model variants of the winning rung with
    same-day single requests through the scheduler queue — the fleet
    worker's request shape (ISSUE 15). QPS decides; 0ms (immediate
    dispatch) is always raced."""
    import dataclasses
    import threading

    from factorvae_tpu.serve.daemon import ScoringDaemon, TickScheduler

    cfg2 = dataclasses.replace(
        cfg, train=dataclasses.replace(cfg.train,
                                       seed=cfg.train.seed + 1000))
    keys = [
        reg.register_params(params, cfg, precision=precision),
        reg.register_params(params, cfg2, precision=precision),
    ]
    daemon = ScoringDaemon(reg, ds)
    day = int(day_idx[-1])
    per_client = max(10, 5 * reps)
    measured = {}
    best_tick, best_qps = SERVE_TICK_CANDIDATES[0], None
    for tick in SERVE_TICK_CANDIDATES:
        sched = TickScheduler(daemon, tick_ms=tick,
                              max_tick_batch=SERVE_TICK_MAX_BATCH)
        try:
            def client(tid, n):
                for i in range(n):
                    sched.submit([{"model": keys[(tid + i) % 2],
                                   "day": day, "top": 3}])

            # warmup: compile the fused fleet programs this load fuses
            warm = [threading.Thread(target=client, args=(t, 4))
                    for t in range(SERVE_TICK_CLIENTS)]
            for t in warm:
                t.start()
            for t in warm:
                t.join()
            threads = [threading.Thread(target=client,
                                        args=(t, per_client))
                       for t in range(SERVE_TICK_CLIENTS)]
            t0 = time.time()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            qps = SERVE_TICK_CLIENTS * per_client / (time.time() - t0)
        finally:
            sched.close()
        measured[f"tick{tick:g}ms"] = round(qps, 1)
        _log(logger, "autotune_serve_tick_candidate", shape=name,
             tick_ms=tick, qps=round(qps, 1))
        if best_qps is None or qps > best_qps:
            best_tick, best_qps = tick, qps
    return {
        "tick_ms": best_tick,
        "max_tick_batch": SERVE_TICK_MAX_BATCH,
        "measured": measured,
        "source": f"scheduler race ({SERVE_TICK_CLIENTS} concurrent "
                  f"clients, {precision}): best tick_ms={best_tick:g} "
                  f"at {best_qps:,.0f} req/s",
    }


def _time_serial_mesh(shape: dict, train_knobs: dict, dps: int,
                      days: int, reps: int, mesh=None) -> float:
    """Seconds per trained day for one (mesh-or-none, days_per_step)
    operating point on the winning train knobs (compile excluded)."""
    import jax

    from factorvae_tpu.train import Trainer
    from factorvae_tpu.utils.logging import MetricsLogger

    cfg, ds = _setup(shape, train_knobs["compute_dtype"],
                     train_knobs["flatten_days"], dps, days)
    trainer = Trainer(cfg, ds, mesh=mesh, logger=MetricsLogger(echo=False))
    state = trainer.init_state()
    state, m = trainer._train_epoch(state, trainer._epoch_orders(0))  # warmup
    jax.block_until_ready(m["loss"])
    t0 = time.time()
    for e in range(1, 1 + reps):
        state, m = trainer._train_epoch(state, trainer._epoch_orders(e))
    jax.block_until_ready(m["loss"])
    return (time.time() - t0) / (reps * days)


def race_mesh(name: str, shape: dict, train_knobs: dict,
              days: int, reps: int, logger=None) -> dict:
    """Race mesh shapes (no-mesh + every data x stock factorization of
    the visible devices, compose.mesh_shape_candidates); return the
    row's `mesh` block, or {'data_axis': 0, 'stock_axis': 0} when
    no-mesh wins (no block is persisted then — the conservative
    default).

    Serial day-dp scales days_per_step per candidate
    (compose.compatible_days_per_step) — and the NO-MESH side is raced
    at every scaled dps too, so the winner is a mesh-vs-no-mesh
    comparison at matched batch semantics, not a larger-batch speedup
    in disguise. The winner's dps is part of the block
    (`days_per_step`): a persisted mesh shape must ship with the day
    batch it was measured at, or the row would be self-incompatible
    (compose.validate rejects dps=1 on a 2-way 'data' axis)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from factorvae_tpu.parallel.compose import (
        compatible_days_per_step,
        mesh_shape_candidates,
    )

    n_dev = len(jax.devices())
    base_dps = train_knobs["days_per_step"]
    mesh_cells = [(dp, sp) for dp, sp in mesh_shape_candidates(n_dev)
                  if (dp, sp) != (1, 1)]
    # no-mesh baselines at EVERY dps a mesh cell will run at (base
    # first): always in the raced set, so a persisted winner can never
    # regress the single-device path at matched semantics.
    none_dps = sorted({base_dps} | {
        compatible_days_per_step(base_dps, dp) for dp, _ in mesh_cells})
    measured = {}
    best, best_sec, best_dps = (0, 0), None, base_dps
    for dps in none_dps:
        sec = _time_serial_mesh(shape, train_knobs, dps, days, reps)
        key = "none" if dps == base_dps else f"none_dps{dps}"
        measured[key] = round(sec, 5)
        _log(logger, "autotune_mesh_candidate", shape=name, candidate=key,
             s_per_day=round(sec, 5))
        if best_sec is None or sec < best_sec:
            best, best_sec, best_dps = (0, 0), sec, dps
    for dp, sp in mesh_cells:
        dps = compatible_days_per_step(base_dps, dp)
        mesh = Mesh(np.asarray(jax.devices()[:dp * sp]).reshape(dp, sp),
                    ("data", "stock"))
        sec = _time_serial_mesh(shape, train_knobs, dps, days, reps,
                                mesh=mesh)
        key = f"mesh_{dp}x{sp}_dps{dps}"
        measured[key] = round(sec, 5)
        _log(logger, "autotune_mesh_candidate", shape=name, candidate=key,
             s_per_day=round(sec, 5))
        if sec < best_sec:
            best, best_sec, best_dps = (dp, sp), sec, dps
    label = ("none" if best == (0, 0) else f"{best[0]}x{best[1]}")
    return {
        "data_axis": best[0],
        "stock_axis": best[1],
        "days_per_step": best_dps,
        "measured": measured,
        "source": f"mesh race on {train_knobs['compute_dtype']} "
                  f"flat={int(train_knobs['flatten_days'])} over {n_dev} "
                  f"devices (dps-matched no-mesh baselines): best "
                  f"{label} dps{best_dps} at {best_sec:.4f} s/day",
    }


def race_fleet(name: str, shape: dict, train_knobs: dict,
               days: int, reps: int, logger=None) -> dict:
    """Race `seeds_per_program` over FLEET_CANDIDATES; return the row's
    `fleet` block (winner + every candidate timing for audit)."""
    measured = {}
    best_s, best_wps = 1, None
    for s in FLEET_CANDIDATES:
        wps = time_fleet(shape, train_knobs, s, days, reps)
        measured[f"S={s}"] = round(wps, 1)
        _log(logger, "autotune_fleet_candidate", shape=name, seeds=s,
             aggregate_windows_per_sec_seed=round(wps, 1))
        if best_wps is None or wps > best_wps:
            best_s, best_wps = s, wps
    return {
        "seeds_per_program": best_s,
        "measured": measured,
        "source": f"fleet race on {train_knobs['compute_dtype']} "
                  f"flat={int(train_knobs['flatten_days'])} "
                  f"dps{train_knobs['days_per_step']}: best S={best_s} "
                  f"at {best_wps:,.0f} w/s·seed",
    }


def _existing_measured_row(shape: dict, platform: str):
    """First persisted FILE row matching this (platform, shape, width)
    — the row whose winners a --mesh race should extend, not re-race.
    Builtins are excluded (they live in code and carry no measured
    dict; a shape only they cover gets a fresh full race)."""
    from factorvae_tpu import plan as planlib

    shp = planlib.ShapeKey(
        num_features=shape["features"], seq_len=shape["seq_len"],
        hidden_size=shape["hidden"], num_factors=shape["factors"],
        num_portfolios=shape["portfolios"], n_stocks=int(shape["stocks"]))
    for row in planlib._read_rows(planlib.table_path()):
        if planlib._match(row, shp, platform):
            return row
    return None


def race_shape(name: str, shape: dict, days: int, reps: int,
               fleet: bool = False, stream: bool = False,
               mesh: bool = False, serve: bool = False,
               hyper: bool = False, train_precision: bool = False,
               kernels: bool = False, remat: bool = False,
               logger=None) -> dict:
    """Race all candidates for one shape at ONE width (`shape['stocks']`
    must be a scalar here — `race_widths` expands lists); return a
    plan-table row.

    With ``mesh=True`` and an ALREADY-MEASURED row covering this
    (platform, shape, width), the train/score knobs (and any
    fleet/stream blocks) are REUSED from that row and only the mesh
    race runs: --mesh forces a virtual multi-device rig on CPU hosts,
    and re-timing the single-program knob races there could silently
    flip winners that were measured on the real device layout — and
    would drop the row's existing fleet/stream blocks.
    """
    from factorvae_tpu.plan import ShapeKey, pad_target_policy, platform_kind

    plat = platform_kind()
    if mesh:
        prior = _existing_measured_row(shape, plat)
        if prior is not None:
            train_knobs = dict(prior["train"])
            mesh_block = race_mesh(name, shape, train_knobs, days, reps,
                                   logger=logger)
            row = {k: v for k, v in prior.items()}
            row.setdefault("measured", {})
            if isinstance(row["measured"], dict):
                row["measured"] = dict(row["measured"],
                                       mesh=mesh_block.pop("measured"))
            else:
                mesh_block.pop("measured")
            row.pop("mesh", None)
            # a re-race REPLACES any previous mesh sentence instead of
            # accreting one per run
            prior_src = str(prior.get("source", "plan table"))
            prior_src = prior_src.split("; mesh race")[0]
            row["source"] = (prior_src +
                             f"; {mesh_block['source']} "
                             f"(raced at n={shape['stocks']})")
            if mesh_block["data_axis"] > 0 and mesh_block["stock_axis"] > 0:
                row["mesh"] = {
                    "data_axis": mesh_block["data_axis"],
                    "stock_axis": mesh_block["stock_axis"],
                    "days_per_step": mesh_block["days_per_step"]}
            return row
    measured: dict = {"train": {}, "score": {}}

    best_train, best_train_key, best_warmup = None, None, None
    measured["train_warmup_s"] = {}
    for cand in TRAIN_CANDIDATES:
        for dtype in DTYPES:
            key = (f"flat={int(cand['flatten_days'])}"
                   f"_dps{cand['days_per_step']}_{dtype}")
            sec, warmup = time_train(shape, dtype, cand["flatten_days"],
                                     cand["days_per_step"], days, reps)
            measured["train"][key] = round(sec, 5)
            # compile-cost provenance rides NEXT TO the rates (not
            # inside the winner block — race_widths merges rows on
            # identical winners, and two widths' warmups always differ)
            measured["train_warmup_s"][key] = round(warmup, 3)
            _log(logger, "autotune_train_candidate", shape=name,
                 candidate=key, s_per_day=round(sec, 5),
                 compile_warmup_s=round(warmup, 3))
            if best_train is None or sec < best_train:
                best_train = sec
                best_train_key = {**cand, "compute_dtype": dtype}
                best_warmup = warmup

    best_score, best_score_key = None, None
    for cand in SCORE_CANDIDATES:
        for dtype in DTYPES:
            key = f"flat={int(cand['flatten_days'])}_{dtype}"
            ws = time_score(shape, dtype, cand["flatten_days"], days, reps)
            measured["score"][key] = round(ws, 1)
            _log(logger, "autotune_score_candidate", shape=name,
                 candidate=key, windows_per_sec=round(ws, 1))
            if best_score is None or ws > best_score:
                best_score = ws
                best_score_key = {**cand, "compute_dtype": dtype}

    fleet_block = None
    if fleet:
        fleet_block = race_fleet(name, shape, best_train_key, days,
                                 reps, logger=logger)
    hyper_block = None
    if hyper:
        hyper_block = race_hyper(name, shape, best_train_key, days,
                                 reps, logger=logger)
    stream_block = None
    if stream:
        stream_block = race_stream(name, shape, best_train_key, days,
                                   reps, logger=logger)
    serve_block = None
    if serve:
        serve_block = race_serve(name, shape, best_score_key, days,
                                 reps, logger=logger)
    tp_block = None
    if train_precision:
        tp_block = race_train_precision(
            name, shape, best_train_key, measured["train"], days, reps,
            logger=logger)
    mesh_block = None
    if mesh:
        mesh_block = race_mesh(name, shape, best_train_key, days,
                               reps, logger=logger)
    kernels_block = None
    if kernels:
        # A crashed race leg propagates LOUDLY here — a silent fallback
        # to the static envelope would persist an unmeasured verdict as
        # if it were measured (bench.py --kernels is the lane that
        # degrades gracefully, via its kernels_race_failed metric).
        kernels_block = race_kernels_block(name, shape, best_train_key,
                                           reps, logger=logger)
    remat_block = None
    if remat:
        remat_block = race_remat(name, shape, best_train_key, days,
                                 reps, logger=logger)

    shp = ShapeKey(
        num_features=shape["features"], seq_len=shape["seq_len"],
        hidden_size=shape["hidden"], num_factors=shape["factors"],
        num_portfolios=shape["portfolios"], n_stocks=shape["stocks"])
    if fleet_block is not None:
        measured["fleet"] = fleet_block.pop("measured")
    if hyper_block is not None:
        measured["hyper"] = hyper_block.pop("measured")
    if stream_block is not None:
        measured["stream"] = stream_block.pop("measured")
    if serve_block is not None:
        measured["serve"] = {"rates": serve_block.pop("measured"),
                             "fidelity": serve_block.pop("fidelity"),
                             "tick": serve_block.pop("tick_measured")}
    if tp_block is not None:
        measured["train_precision"] = tp_block.pop("measured")
    if mesh_block is not None:
        measured["mesh"] = mesh_block.pop("measured")
    if kernels_block is not None:
        measured["kernels"] = kernels_block.pop("measured")
    if remat_block is not None:
        measured["train_remat"] = remat_block.pop("measured")
    row = {
        "platform": plat,
        "shape": {"c": shp.num_features, "t": shp.seq_len,
                  "h": shp.hidden_size, "k": shp.num_factors,
                  "m": shp.num_portfolios},
        "n_min": shp.n_stocks, "n_max": shp.n_stocks,
        "pad_target": pad_target_policy(shp.n_stocks, plat),
        "train": best_train_key,
        "score": best_score_key,
        "measured": measured,
        "source": f"autotune_plan {name} n={shp.n_stocks} on {plat} "
                  f"(days={days}, reps={reps}): "
                  f"train {best_train:.4f} s/day "
                  f"(compile+first epoch {best_warmup:.1f}s), "
                  f"score {best_score:,.0f} w/s",
    }
    if fleet_block is not None:
        row["fleet"] = {"seeds_per_program":
                        fleet_block["seeds_per_program"]}
        row["source"] += f"; {fleet_block['source']}"
    if hyper_block is not None:
        row["hyper"] = {"lanes_per_program":
                        hyper_block["lanes_per_program"]}
        row["source"] += f"; {hyper_block['source']}"
    if stream_block is not None:
        row["stream"] = {"panel_residency": stream_block["panel_residency"],
                         "chunk_days": stream_block["chunk_days"]}
        row["source"] += f"; {stream_block['source']}"
    if serve_block is not None:
        row["source"] += f"; {serve_block['source']}"
        # f32 winners persist NO precision key (the conservative
        # default — plan_for resolves an absent key to float32, which
        # is bitwise the offline scan), same rule as no-mesh winners.
        # The scheduler knobs (ISSUE 15) always persist: they are
        # precision-independent and 0ms is itself a measured winner.
        row["serve"] = {
            "tick_ms": serve_block["tick_ms"],
            "max_tick_batch": serve_block["max_tick_batch"],
        }
        if serve_block["precision"] != "float32":
            row["serve"]["precision"] = serve_block["precision"]
    if tp_block is not None:
        row["source"] += f"; {tp_block['source']}"
        # f32 winners persist NO block (the conservative default —
        # plan_for resolves an absent block to "" = no verdict, and the
        # TrainConfig dtype stays None), the same rule as serve: a bf16
        # training rung is a measured win past the Rank-IC floor, never
        # inferred.
        if tp_block["precision"] != "float32":
            row["train_precision"] = {
                "precision": tp_block["precision"],
                "fidelity": tp_block["fidelity"],
            }
    if mesh_block is not None:
        row["source"] += f"; {mesh_block['source']}"
        if mesh_block["data_axis"] > 0 and mesh_block["stock_axis"] > 0:
            # no-mesh winners persist NO block (the conservative
            # default; plan_for then leaves MeshConfig alone). The
            # winner's (scaled) days_per_step ships WITH the shape —
            # a 2-way 'data' axis next to the train race's dps=1 would
            # be a self-incompatible row (compose.validate).
            row["mesh"] = {"data_axis": mesh_block["data_axis"],
                           "stock_axis": mesh_block["stock_axis"],
                           "days_per_step": mesh_block["days_per_step"]}
    if kernels_block is not None:
        row["source"] += f"; {kernels_block['source']}"
        # The block persists EVEN when xla sweeps both ops: a measured
        # xla verdict upgrades the row from "assumed" to "raced on this
        # rig", and pins use_pallas_* off instead of leaving the static
        # envelope to guess. No regression is possible — xla was in the
        # candidate set, so the winner is never slower than fallback.
        row["kernels"] = {"gru": kernels_block["gru"],
                          "attention": kernels_block["attention"]}
    if remat_block is not None:
        row["source"] += f"; {remat_block['source']}"
        # "none" winners persist NO block (the conservative default —
        # plan_for resolves an absent block to TrainConfig.remat's own
        # default, which IS "none"): a remat rung ships only past a
        # measured per-trained-day win, exactly the ROADMAP item 3
        # gate. When the win came from the doubled batch the freed
        # peak_bytes admits, the winning days_per_step ships WITH the
        # row (overriding the train race's dps) so the end-to-end
        # operating point that actually won is what plan_for resolves.
        if remat_block["remat"] != "none":
            row["train_remat"] = {"remat": remat_block["remat"]}
            if remat_block["days_per_step"] != best_train_key[
                    "days_per_step"]:
                row["train"] = dict(best_train_key,
                                    days_per_step=remat_block[
                                        "days_per_step"])
    return row


def race_widths(name: str, shape: dict, days: int, reps: int,
                fleet: bool = False, stream: bool = False,
                mesh: bool = False, serve: bool = False,
                hyper: bool = False, train_precision: bool = False,
                kernels: bool = False, remat: bool = False,
                logger=None) -> list:
    """Race every width in `shape['stocks']` (scalar or list) and merge
    adjacent widths with IDENTICAL winners into one [n_min, n_max]
    envelope row — both bounds measured, no extrapolation beyond them
    (the kernel-envelope precedent). Widths whose winners differ stay
    separate single-width rows: no interpolation between them."""
    widths = shape["stocks"]
    if not isinstance(widths, (list, tuple)):
        widths = [widths]
    rows = [race_shape(name, {**shape, "stocks": int(w)}, days, reps,
                       fleet=fleet, stream=stream, mesh=mesh,
                       serve=serve, hyper=hyper,
                       train_precision=train_precision, kernels=kernels,
                       remat=remat, logger=logger)
            for w in sorted(widths)]
    merged = [rows[0]]
    for r in rows[1:]:
        p = merged[-1]
        if (r["train"], r["score"], r.get("fleet"), r.get("stream"),
                r.get("mesh"), r.get("serve"), r.get("hyper"),
                r.get("train_precision"), r.get("kernels"),
                r.get("train_remat")) != (
                p["train"], p["score"], p.get("fleet"), p.get("stream"),
                p.get("mesh"), p.get("serve"), p.get("hyper"),
                p.get("train_precision"), p.get("kernels"),
                p.get("train_remat")):
            merged.append(r)
            continue
        if not any(k.startswith("n=") for k in p["measured"]):
            p["measured"] = {f"n={p['n_max']}": p["measured"]}
        p["measured"][f"n={r['n_min']}"] = r["measured"]
        p["n_max"] = r["n_max"]
        # pad_target was measured at one width; the merged envelope
        # spans several, so let plan_for re-derive it per queried width.
        p.pop("pad_target", None)
        p["source"] += f"; identical winners at n={r['n_min']}"
    return merged


def main() -> int:
    p = argparse.ArgumentParser(
        description="bounded per-backend micro-autotune -> PLAN_TABLE.json")
    p.add_argument("--config", choices=sorted(SHAPES), default="flagship")
    p.add_argument("--all", action="store_true",
                   help="race every preset shape (4x the runtime)")
    p.add_argument("--days", type=int, default=8,
                   help="synthetic panel days per timed run")
    p.add_argument("--reps", type=int, default=2,
                   help="timed repetitions per candidate")
    p.add_argument("--out", default=None,
                   help="plan table path (default: the planner's own "
                        "resolution — FACTORVAE_PLAN_TABLE or "
                        "PLAN_TABLE.json at the repo root)")
    p.add_argument("--fleet", action="store_true",
                   help="also race the seed-parallel fleet knob "
                        "(seeds_per_program in {1, 2, 4, 8}, "
                        "train/fleet.py) on each shape's winning train "
                        "knobs; the aggregate-seed-throughput winner is "
                        "persisted on the row's 'fleet' block "
                        "(plan_for -> Plan.seeds_per_program; rows "
                        "without the block resolve to serial)")
    p.add_argument("--hyper", action="store_true",
                   help="also race the heterogeneous-lane hyper-fleet "
                        "knob (lanes_per_program in {1, 2, 4, 8}, "
                        "train/fleet.py lane_configs; ISSUE 12) on each "
                        "shape's winning train knobs; the aggregate "
                        "config-throughput winner is persisted on the "
                        "row's 'hyper' block (plan_for -> "
                        "Plan.lanes_per_program; rows without the block "
                        "resolve to 0 = fall back to seeds_per_program)")
    p.add_argument("--stream", action="store_true",
                   help="also race the panel residency (hbm vs the "
                        "out-of-core stream path at chunk sizes "
                        f"{STREAM_CHUNK_CANDIDATES}, data/stream.py) on "
                        "each shape's winning train knobs; the winner is "
                        "persisted on the row's 'stream' block (plan_for "
                        "-> Plan.panel_residency/stream_chunk_days; rows "
                        "without the block resolve to hbm)")
    p.add_argument("--mesh", action="store_true",
                   help="also race the mesh shape (no-mesh + every "
                        "data x stock factorization of the visible "
                        "devices, parallel/partition.py) on each "
                        "shape's winning train knobs; a mesh winner is "
                        "persisted on the row's 'mesh' block (plan_for "
                        "-> Plan.mesh_data_axis/mesh_stock_axis; "
                        "no-mesh winners persist NO block, and rows "
                        "without one keep the run's own MeshConfig)")
    p.add_argument("--serve", action="store_true",
                   help="also race the serving-precision ladder "
                        f"({'/'.join(SERVE_PRECISIONS)}, "
                        "serve/registry.py) through the registry "
                        "scoring path on each shape's winning score "
                        "layout; a sub-f32 winner (eligible only past "
                        f"the {SERVE_FIDELITY_FLOOR} rank-fidelity "
                        "floor vs f32) is persisted on the row's "
                        "'serve' block (plan_for -> "
                        "Plan.serve_precision; f32 winners persist NO "
                        "block and rows without one serve float32 — "
                        "bitwise the offline scan)")
    p.add_argument("--train_precision", action="store_true",
                   help="also race the TRAINING-precision ladder "
                        "(f32 oracle vs the bf16 mixed master-weight "
                        "path, train/state.py; ISSUE 16) on each "
                        "shape's winning train knobs: two short "
                        "trainings from one init, each scored through "
                        "the deterministic f32 scan; a bf16 winner "
                        "(eligible only when faster AND past the "
                        f"{TRAIN_FIDELITY_FLOOR} masked-Spearman "
                        "Rank-IC floor vs the f32-trained model) is "
                        "persisted on the row's 'train_precision' "
                        "block (plan_for -> Plan.train_compute_dtype; "
                        "f32 winners persist NO block and rows without "
                        "one leave TrainConfig.compute_dtype alone)")
    p.add_argument("--kernels", action="store_true",
                   help="also race {pallas, xla} x {gru, attention} "
                        "forward+backward (scripts/race_kernels.py "
                        "engine; ISSUE 19) at each shape's production "
                        "operating point on the current backend; the "
                        "measured winners persist on the row's "
                        "'kernels' block (plan_for -> Plan.kernel_gru/"
                        "kernel_attention, pinning use_pallas_*; the "
                        "pallas_*_wins predicates read the verdict "
                        "FIRST and rows without one fall back to the "
                        "static round-2 envelope — docs/kernels.md). "
                        "xla is always in the raced set, so a "
                        "persisted verdict never regresses a shape")
    p.add_argument("--remat", action="store_true",
                   help="also race the rematerialization rung "
                        f"({'/'.join(REMAT_CANDIDATES)}, train/loop.py "
                        "jax.checkpoint; ISSUE 19) on each shape's "
                        "winning train knobs, judged on wall-clock AND "
                        "on whether freed compiled peak_bytes admits a "
                        "doubled days_per_step that wins end-to-end; a "
                        "non-none winner is persisted on the row's "
                        "'train_remat' block (plan_for -> "
                        "Plan.train_remat; 'none' winners persist NO "
                        "block and rows without one keep "
                        "TrainConfig.remat's own default)")
    p.add_argument("--mesh_devices", type=int, default=0,
                   help="with --mesh under JAX_PLATFORMS=cpu: force "
                        "this many virtual host-CPU devices (the test-"
                        "rig pattern) so the race covers a real grid; "
                        "default 4. Ignored on accelerators (real "
                        "devices are raced)")
    p.add_argument("--dry_run", action="store_true",
                   help="race and print the rows without persisting")
    p.add_argument("--metrics_jsonl", default=None,
                   help="append race-progress events to this JSONL "
                        "stream (one RUN.jsonl per session: point a "
                        "subsequent cli.py/sweep run at the same file "
                        "and obs.report renders the whole thing)")
    args = p.parse_args()

    from factorvae_tpu.plan import save_rows

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # An EXPLICIT CPU request: route through force_host_devices —
        # the sandbox's axon sitecustomize pins the platform at
        # jax-config level, so the env var alone doesn't switch (see
        # utils/testing.py). When JAX_PLATFORMS is unset, leave jax's
        # auto-detection alone: on a TPU host the race must run on the
        # chip (forcing CPU here would persist platform="cpu" rows a
        # TPU plan_for can never match).
        from factorvae_tpu.utils.testing import force_host_devices

        force_host_devices((args.mesh_devices or 4) if args.mesh else 1)

    # Echo to STDERR: stdout is the table-JSON artifact. Constructed
    # after force_host_devices so the run_meta header records the
    # platform the race actually runs on.
    from factorvae_tpu.utils.logging import (
        MetricsLogger,
        Timeline,
        install_timeline,
    )

    with MetricsLogger(jsonl_path=args.metrics_jsonl, echo=True,
                       echo_to=sys.stderr, run_name="autotune_plan") as lg:
        # Timeline installed for the races: every candidate trainer's
        # jits go through the compile watchdog, so each compile lands a
        # `compile` record in the same stream as the race events — the
        # raced winners' compile provenance, renderable by
        # obs.report/obs.timeline. Capture is DISABLED for the races:
        # each candidate builds fresh jits, so the per-jit replay (a
        # second full XLA compile) would fire once per candidate and
        # nearly double the race wall clock — the provenance consumed
        # here (time_train's warmup = the watchdog's wall_s) doesn't
        # need it.
        from factorvae_tpu.obs.watchdog import capture_disabled

        prev_tl = install_timeline(Timeline(lg))
        try:
            names = sorted(SHAPES) if args.all else [args.config]
            with capture_disabled():
                rows = [r for n in names
                        for r in race_widths(
                            n, SHAPES[n], args.days,
                            args.reps, fleet=args.fleet,
                            stream=args.stream,
                            mesh=args.mesh,
                            serve=args.serve,
                            hyper=args.hyper,
                            train_precision=args.train_precision,
                            kernels=args.kernels,
                            remat=args.remat,
                            logger=lg)]
            print(json.dumps({"rows": rows}, indent=1))
            if args.dry_run:
                lg.log("autotune_dry_run", rows=len(rows),
                       note="table not written")
                return 0
            path = save_rows(rows, path=args.out)
            lg.log("autotune_table_written", rows=len(rows), path=path)
        finally:
            install_timeline(prev_tl)
    return 0


if __name__ == "__main__":
    sys.exit(main())
