"""Differential validation of the built-in backtest simulator vs qlib.

The account simulator (factorvae_tpu/eval/backtest.py) is validated by
scenario tests authored in this repo; the reference's ground truth is
qlib's `TopkDropoutStrategy` + `SimulatorExecutor` (backtest.ipynb cell
6), which is absent from the build sandbox. This script makes the
differential executable the moment a qlib install + data bundle exist
(VERDICT r4 next-#6), at zero marginal cost:

(a) run OUR simulator on an exported scores CSV;
(b) run qlib's strategy/executor on the same signal when qlib is
    importable (and its bundle initialized);
(c) diff the daily return / turnover / cost series within stated
    tolerances, and report per-series max deviations.

When qlib (or its data bundle) is unavailable the script SKIPS cleanly:
it still runs (a), writes the artifact with `qlib_available: false` and
the skip reason, and exits 0 — so it can sit in CI unconditionally.

First scenarios to inspect on a real diff (the simulator's two *chosen
interpretations*, see docs/qlib_handoff.md): the all-NaN-score day and
the drifted-book-no-signal day.

Usage:
    python scripts/qlib_differential.py SCORES.csv [--labels PANEL.pkl]
        [--provider_uri ~/.qlib/qlib_data/cn_data] [--benchmark SH000300]
        [--topk 50] [--n_drop 10] [--out QLIB_DIFFERENTIAL.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import pandas as pd

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Tolerances for the daily-series diff. Sources of benign divergence the
# scenario tests cannot remove: qlib deals at bundle prices with integer
# share rounding (we deal in value space), per-instrument tradable
# calendars richer than our NaN-label approximation, and float
# accumulation order. Structural disagreements (wrong holdings, missed
# rejections) blow straight through these.
TOLERANCES = {
    "return": 5e-4,     # |daily gross return delta|
    # |daily turnover delta|. Two-sided (buy+sell): simulate_topk_account
    # reports (sells+buys)/start_value (backtest.py traded accumulator),
    # matching qlib's convention — NOT the one-side buy fraction the
    # lighter backtest_topk_dropout report uses.
    "turnover": 2e-2,
    "cost": 2e-4,       # |daily cost-rate delta|
}


def load_scores_csv(path: str, labels_path: str | None = None) -> pd.DataFrame:
    """(datetime, instrument)-indexed frame with score [+ LABEL0]."""
    df = pd.read_csv(path, parse_dates=["datetime"])
    df = df.set_index(["datetime", "instrument"]).sort_index()
    if "LABEL0" not in df.columns:
        if not labels_path:
            raise SystemExit("scores CSV has no LABEL0 column; pass --labels "
                             "(a reference-schema panel pickle)")
        from factorvae_tpu.data.panel import load_frame

        frame = load_frame(labels_path)
        df = df.join(frame["LABEL0"], how="left")
    return df


def run_ours(scores: pd.DataFrame, topk: int, n_drop: int, account: float,
             open_cost: float, close_cost: float, min_cost: float,
             limit_threshold: float | None) -> pd.DataFrame:
    """Path (a): the built-in account simulator's report_normal_df-shaped
    report (columns return/turnover/cost, return GROSS of cost)."""
    from factorvae_tpu.eval.backtest import simulate_topk_account

    res = simulate_topk_account(
        scores, topk=topk, n_drop=n_drop, account=account,
        open_cost=open_cost, close_cost=close_cost, min_cost=min_cost,
        limit_threshold=limit_threshold)
    return res.report


def run_qlib(scores: pd.DataFrame, provider_uri: str, benchmark: str,
             topk: int, n_drop: int, account: float, open_cost: float,
             close_cost: float, min_cost: float,
             limit_threshold: float | None):
    """Path (b): qlib's own simulator on the same signal.

    Returns (report_df, None) on success or (None, reason) when qlib or
    its data bundle is unavailable — the caller skips cleanly. API per
    docs/qlib_handoff.md (qlib >= 0.9 daily convenience wrapper; the
    reference notebook's lower-level backtest+SimulatorExecutor reaches
    the same simulator)."""
    try:
        import qlib  # noqa: F401
    except ImportError as e:
        return None, f"qlib not importable: {e}"
    try:
        import qlib as _qlib
        from qlib.contrib.evaluate import backtest_daily
        from qlib.contrib.strategy import TopkDropoutStrategy

        _qlib.init(provider_uri=os.path.expanduser(provider_uri),
                   region="cn")
    except Exception as e:  # missing bundle, version drift, ...
        return None, f"qlib init failed ({type(e).__name__}: {e})"

    try:
        pred = scores["score"].dropna()
        dates = pred.index.get_level_values(0)
        strategy = TopkDropoutStrategy(signal=pred, topk=topk,
                                       n_drop=n_drop)
        report, _positions = backtest_daily(
            start_time=str(dates.min().date()),
            end_time=str(dates.max().date()),
            strategy=strategy,
            account=account,
            benchmark=benchmark,
            exchange_kwargs=dict(
                limit_threshold=limit_threshold,
                deal_price="close",
                open_cost=open_cost, close_cost=close_cost,
                min_cost=min_cost,
            ),
        )
        return report, None
    except Exception as e:
        return None, f"qlib backtest failed ({type(e).__name__}: {e})"


def diff_reports(ours: pd.DataFrame, theirs: pd.DataFrame,
                 tolerances: dict = TOLERANCES) -> dict:
    """Path (c): per-series diff on the shared trading days.

    Both inputs are report_normal_df-shaped (columns return / turnover /
    cost; qlib's `return` is gross of cost, as is ours)."""
    idx = ours.index.intersection(theirs.index)
    out = {"shared_days": int(len(idx)),
           "ours_only_days": int(len(ours.index.difference(theirs.index))),
           "qlib_only_days": int(len(theirs.index.difference(ours.index))),
           "series": {}, "pass": True}
    for col, tol in tolerances.items():
        if col not in ours.columns or col not in theirs.columns:
            out["series"][col] = {"available": False}
            out["pass"] = False
            continue
        a = ours.loc[idx, col].astype(float)
        b = theirs.loc[idx, col].astype(float)
        d = (a - b).abs()
        worst = d.idxmax() if len(d) else None
        ok = bool((d <= tol).all()) if len(d) else True
        out["series"][col] = {
            "available": True,
            "tolerance": tol,
            "max_abs_diff": float(d.max()) if len(d) else 0.0,
            "mean_abs_diff": float(d.mean()) if len(d) else 0.0,
            "days_within_tol": int((d <= tol).sum()),
            "worst_day": str(worst) if worst is not None else None,
            "pass": ok,
        }
        out["pass"] = out["pass"] and ok
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("scores_csv")
    ap.add_argument("--labels", default=None)
    ap.add_argument("--provider_uri", default="~/.qlib/qlib_data/cn_data")
    ap.add_argument("--benchmark", default="SH000300")
    ap.add_argument("--topk", type=int, default=50)
    ap.add_argument("--n_drop", type=int, default=10)
    ap.add_argument("--account", type=float, default=1e8)
    ap.add_argument("--open_cost", type=float, default=0.0005)
    ap.add_argument("--close_cost", type=float, default=0.0015)
    ap.add_argument("--min_cost", type=float, default=5.0)
    ap.add_argument("--limit_threshold", type=float, default=0.095)
    ap.add_argument("--out", default="QLIB_DIFFERENTIAL.json")
    args = ap.parse_args(argv)

    kw = dict(topk=args.topk, n_drop=args.n_drop, account=args.account,
              open_cost=args.open_cost, close_cost=args.close_cost,
              min_cost=args.min_cost, limit_threshold=args.limit_threshold)

    scores = load_scores_csv(args.scores_csv, args.labels)
    ours = run_ours(scores, **kw)
    print(f"[qlib-diff] ours: {len(ours)} trading days, "
          f"cum return {float(ours['return'].sum()):+.4f} (sum, gross)")

    theirs, reason = run_qlib(scores, args.provider_uri, args.benchmark,
                              **kw)
    results = {
        "scores_csv": args.scores_csv,
        "params": kw,
        "tolerances": TOLERANCES,
        "ours_days": int(len(ours)),
        "qlib_available": theirs is not None,
    }
    if theirs is None:
        results["skip_reason"] = reason
        print(f"[qlib-diff] SKIP qlib leg: {reason}")
        print("[qlib-diff] path (a) ran; differential pending a qlib "
              "install + data bundle (docs/qlib_handoff.md)")
    else:
        results["diff"] = diff_reports(ours, theirs)
        verdict = "PASS" if results["diff"]["pass"] else "FAIL"
        print(f"[qlib-diff] {verdict} over "
              f"{results['diff']['shared_days']} shared days")
        for col, rec in results["diff"]["series"].items():
            if rec.get("available"):
                print(f"[qlib-diff]   {col}: max|Δ|={rec['max_abs_diff']:.2e} "
                      f"(tol {rec['tolerance']:.0e}) "
                      f"{'ok' if rec['pass'] else 'EXCEEDED'}")

    with open(args.out, "w") as f:
        json.dump(results, f, indent=1, default=str)
    print(f"[qlib-diff] wrote {args.out}")
    # Skip (qlib absent) exits 0 so this can run unconditionally in CI;
    # a failed differential exits 1.
    return 0 if theirs is None or results["diff"]["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
