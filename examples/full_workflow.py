"""End-to-end example: the complete reference workflow on synthetic data.

Mirrors what a user of the reference does across main.py + the backtest
notebook — train, export scores, Rank-IC, top-k backtest — through this
framework's Python API (the CLI covers the same flow from the shell).

Run:  python examples/full_workflow.py  [--real /path/to/csi_data.pkl]
"""

from __future__ import annotations

import argparse
import tempfile


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--real", default=None, help="path to a reference-schema pickle")
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--cpu", action="store_true",
                    help="force host-CPU devices (also auto-applied in "
                         "sandboxes whose TPU plugin pins jax_platforms)")
    args = ap.parse_args()

    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    if args.cpu or os.environ.get("PALLAS_AXON_POOL_IPS"):
        from factorvae_tpu.utils.testing import force_host_devices

        force_host_devices(1)

    from factorvae_tpu.config import Config, DataConfig, ModelConfig, TrainConfig
    from factorvae_tpu.data import PanelDataset, build_panel, load_frame, synthetic_frame
    from factorvae_tpu.eval import (
        RankIC,
        export_scores,
        generate_prediction_scores,
        topk_dropout_backtest,
    )
    from factorvae_tpu.train import Trainer
    from factorvae_tpu.utils.logging import MetricsLogger

    workdir = tempfile.mkdtemp(prefix="factorvae_example_")

    if args.real:
        frame = load_frame(args.real)
        cfg = Config(train=TrainConfig(num_epochs=args.epochs, save_dir=workdir))
    else:
        frame = synthetic_frame(
            num_days=60, num_instruments=20, num_features=16,
            missing_prob=0.05, signal=0.7, seed=0,
            label_scale=0.02,  # daily-return-like magnitudes for the demo
        )
        cfg = Config(
            model=ModelConfig(num_features=16, hidden_size=16, num_factors=8,
                              num_portfolios=12, seq_len=8),
            data=DataConfig(seq_len=8, start_time=None, fit_end_time="2020-02-28",
                            val_start_time="2020-03-01", val_end_time=None),
            train=TrainConfig(num_epochs=args.epochs, lr=1e-3, save_dir=workdir),
        )

    dataset = PanelDataset(build_panel(frame), seq_len=cfg.data.seq_len)
    trainer = Trainer(cfg, dataset, logger=MetricsLogger())
    state, out = trainer.fit()

    scores = generate_prediction_scores(
        state.params, cfg, dataset, stochastic=False, with_labels=True
    )
    csv_path = export_scores(scores, cfg, out_dir=f"{workdir}/scores")
    ic = RankIC(scores.dropna(), "LABEL0", "score")
    bt = topk_dropout_backtest(scores, topk=5, n_drop=2)

    print(f"\nscores csv : {csv_path}")
    print(f"rank-ic    : {float(ic['RankIC'].iloc[0]):+.4f} "
          f"(IR {float(ic['RankIC_IR'].iloc[0]):+.3f})")
    print(f"backtest   : {bt.summary()}")

    # int8 weight-only scoring (ops/quant.py): 4x smaller parameter
    # residency, rank-faithful scores — the serving-oriented path.
    i8 = generate_prediction_scores(
        state.params, cfg, dataset, stochastic=False, int8=True
    )
    rho = scores["score"].corr(i8["score"], method="spearman")
    print(f"int8 path  : rank corr vs f32 = {rho:+.4f}")


if __name__ == "__main__":
    main()
