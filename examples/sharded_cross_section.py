"""Example: cross-section ('stock'-axis) sharding with explicit collectives.

Demonstrates the framework's distributed primitives directly — the same
ops GSPMD inserts automatically in the trainer, written against a named
mesh axis with `jax.shard_map`:

  1. masked softmax over a sharded stock axis (pmax/psum),
  2. the distributed portfolio reduction W^T y,
  3. ring attention over the sharded cross-section (ppermute rotation).

Runs on any device count (virtual CPU mesh here; a TPU slice unchanged).

Run:  python examples/sharded_cross_section.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from factorvae_tpu.utils.testing import force_host_devices

force_host_devices(8)

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

# version-tolerant: `jax.shard_map` is public only from jax 0.6
from factorvae_tpu.parallel.compat import shard_map

from factorvae_tpu.ops.masked import masked_softmax
from factorvae_tpu.parallel.collective_ops import (
    pmax_masked_softmax,
    psum_matvec,
)
from factorvae_tpu.parallel.ring import ring_cross_section_attention


def main() -> None:
    devices = jax.devices()
    mesh = Mesh(np.asarray(devices).reshape(len(devices)), ("stock",))
    print(f"mesh: {len(devices)} x {devices[0].platform} over axis 'stock'")

    rng = np.random.default_rng(0)
    n, m, h, k = 64, 6, 8, 4  # stocks, portfolios, hidden, heads
    weights = jnp.asarray(rng.normal(size=(n, m)), jnp.float32)
    returns = jnp.asarray(rng.normal(size=(n,)) * 0.02, jnp.float32)
    mask = jnp.asarray(rng.random(n) > 0.1)

    # 1) distributed masked softmax over stocks (the encoder's dim=0 softmax)
    dist_softmax = shard_map(
        lambda w, mk: pmax_masked_softmax(w, mk[:, None], "stock", axis=0),
        mesh=mesh, in_specs=(P("stock", None), P("stock")),
        out_specs=P("stock", None),
    )
    w_dist = dist_softmax(weights, mask)
    w_ref = masked_softmax(weights, mask[:, None], axis=0)
    print("softmax max|delta|:", float(jnp.abs(w_dist - w_ref).max()))

    # 2) distributed portfolio returns y_p = W^T y
    dist_portfolio = shard_map(
        lambda w, y: psum_matvec(w, y, "stock"),
        mesh=mesh, in_specs=(P("stock", None), P("stock")), out_specs=P(),
    )
    y_p = dist_portfolio(w_dist, jnp.where(mask, returns, 0.0))
    print("portfolio returns:", np.round(np.asarray(y_p), 5))

    # 3) ring attention: K queries over the sharded cross-section
    q = jnp.asarray(rng.normal(size=(k, h)), jnp.float32)
    keys = jnp.asarray(rng.normal(size=(n, h)), jnp.float32)
    vals = jnp.asarray(rng.normal(size=(n, h)), jnp.float32)
    ring = shard_map(
        lambda kl, vl, ml: ring_cross_section_attention(q, kl, vl, ml, "stock"),
        mesh=mesh,
        in_specs=(P("stock", None), P("stock", None), P("stock")),
        out_specs=P(), check_vma=False,
    )
    ctx = ring(keys, vals, mask)
    print("ring attention context:", ctx.shape, "finite:",
          bool(np.isfinite(np.asarray(ctx)).all()))


if __name__ == "__main__":
    main()
